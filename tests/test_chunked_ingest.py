"""Out-of-core data plane (ISSUE 10): chunked columnar ingestion,
streamed bin quantization, double-buffered H2D prefetch, chunk-local
splits.

The load-bearing contracts:
- chunked == monolithic BIT-parity for fit / predict / randomSplit
  membership across chunkRows ∈ {64, 1000, all} (the sketch is exact on
  small data, split draws are stateless per global row, and everything
  downstream of quantization is the same code path);
- sketch-mode (compressed) bin edges within one bin width of exact;
- prefetch overlap proven from ingest.dispatch/ingest.drain event order;
- device residency ledger-bounded by the COMPACT representation
  (chunk_stage + bin_cache peaks << raw float bytes);
- the bin cache is REUSED across ingests of the same content (LRU hit,
  zero fresh H2D) and the ingest memo skips repeat passes.
"""

import numpy as np
import pytest

from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.frame._chunks import (ArrayChunkSource, DatasetSketch,
                                   FeatureSketch, FilteredChunkSource,
                                   GeneratorChunkSource, chunk_random_split,
                                   split_assignments)
from sml_tpu.frame.sampling import row_uniforms
from sml_tpu.ml._chunked import (cross_validate_chunked, fit_ensemble_chunked,
                                 ingest_source, predict_chunked)
from sml_tpu.ml._tree_models import _fit_ensemble
from sml_tpu.ml.tree_impl import make_bins


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    n, F = 3000, 6
    X = rng.normal(size=(n, F))
    y = X[:, 0] * 2 - X[:, 1] ** 2 + rng.normal(0, 0.2, n)
    return X, y


@pytest.fixture()
def recorder_on():
    import sml_tpu.obs as obs
    old = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    obs.reset()
    yield obs
    GLOBAL_CONF.set("sml.obs.enabled", old)


def _trees_equal(a, b):
    for ta, tb in zip(a.trees, b.trees):
        assert np.array_equal(ta.split_feature, tb.split_feature)
        assert np.array_equal(ta.split_bin, tb.split_bin)
        assert np.array_equal(ta.leaf_value, tb.leaf_value)


# --------------------------------------------------------------- bit parity
def test_ingest_edges_and_bins_bit_parity(data):
    """Exact-mode sketch edges + the streamed per-chunk quantization are
    bit-identical to the monolithic make_bins on small data."""
    X, y = data
    binned_m, binning_m = make_bins(X, np.asarray(y, np.float32), 32)
    ing = ingest_source(ArrayChunkSource(X, y, chunk_rows=64), 32)
    assert ing.stats["sketch_exact"]
    assert np.array_equal(ing.binning.edges, binning_m.edges)
    assert np.array_equal(ing.binned, binned_m)
    assert np.array_equal(ing.y, np.asarray(y, np.float32))


@pytest.mark.parametrize("chunk_rows", [64, 1000, None])
def test_fit_and_predict_bit_parity(data, chunk_rows):
    """The chunked fit produces the SAME forest (bit-for-bit trees) and
    the SAME predictions as the monolithic path, for any chunking —
    including `None` (one chunk, the degenerate monolithic layout)."""
    X, y = data
    spec_m = _fit_ensemble(X, y, categorical={}, max_depth=4, max_bins=32,
                           min_instances=1, min_info_gain=0.0, n_trees=5,
                           feature_k=None, bootstrap=True, subsample=1.0,
                           seed=7, loss="squared")
    src = ArrayChunkSource(X, y, chunk_rows=chunk_rows)
    spec_c = fit_ensemble_chunked(src, max_depth=4, max_bins=32, n_trees=5,
                                  bootstrap=True, seed=7)
    _trees_equal(spec_m, spec_c)
    pm = spec_m.predict_margin(X[:500])
    pc = predict_chunked(spec_c, ArrayChunkSource(X[:500],
                                                  chunk_rows=chunk_rows))
    assert np.array_equal(pm, pc)


@pytest.mark.parametrize("chunk_rows", [64, 1000, None])
def test_random_split_membership_bit_parity(data, chunk_rows):
    """Split membership is a pure function of (seed, global row index):
    identical row sets for ANY chunking, disjoint and exhaustive."""
    X, y = data
    cells = split_assignments(42, 0, len(X), [0.7, 0.3])
    src = ArrayChunkSource(X, y, chunk_rows=chunk_rows)
    tr, te = chunk_random_split(src, [0.7, 0.3], 42)
    Xtr = np.concatenate([c[0] for c in tr.chunks()])
    Xte = np.concatenate([c[0] for c in te.chunks()])
    assert np.array_equal(Xtr, X[cells == 0])
    assert np.array_equal(Xte, X[cells == 1])
    assert len(Xtr) + len(Xte) == len(X)


def test_nested_split_chunk_invariant(data):
    """A split OF a split stays chunk-layout-invariant: the filtered
    source numbers rows by filtered position, which is itself
    layout-invariant."""
    X, y = data
    outs = {}
    for cr in (64, 999, None):
        src = ArrayChunkSource(X, y, chunk_rows=cr)
        tr, _ = chunk_random_split(src, [0.8, 0.2], 1)
        sub, _ = chunk_random_split(tr, [0.5, 0.5], 2)
        outs[cr] = np.concatenate([c[0] for c in sub.chunks()])
    assert np.array_equal(outs[64], outs[999])
    assert np.array_equal(outs[64], outs[None])


def test_cv_fold_fits_bit_identical_metrics_close(data):
    """Fold fits are bit-identical across chunkings; the STREAMED rmse
    accumulates per chunk, so metrics agree to reduction-order
    tolerance."""
    X, y = data
    cv_a = cross_validate_chunked(ArrayChunkSource(X, y, chunk_rows=500),
                                  3, 11, max_depth=3, max_bins=16,
                                  n_trees=2, bootstrap=True, seed=5)
    cv_b = cross_validate_chunked(ArrayChunkSource(X, y), 3, 11,
                                  max_depth=3, max_bins=16, n_trees=2,
                                  bootstrap=True, seed=5)
    np.testing.assert_allclose(cv_a["fold_rmse"], cv_b["fold_rmse"],
                               rtol=1e-12)
    assert cv_a["k"] == 3 and len(cv_a["fold_rmse"]) == 3


def test_estimator_fit_chunked_matches_fit(spark, data):
    """Estimator-level surface: RandomForestRegressor.fit_chunked on a
    ChunkSource fits the SAME model as .fit on the materialized frame."""
    import pandas as pd

    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import RandomForestRegressor
    X, y = data
    pdf = pd.DataFrame({f"f{i}": X[:, i] for i in range(X.shape[1])})
    pdf["label"] = y
    df = spark.createDataFrame(pdf)
    va = VectorAssembler(inputCols=[f"f{i}" for i in range(X.shape[1])],
                        outputCol="features")
    rf = RandomForestRegressor(featuresCol="features", labelCol="label",
                               maxDepth=3, maxBins=16, numTrees=3, seed=9)
    m_frame = rf.fit(va.transform(df))
    m_chunk = rf.fit_chunked(ArrayChunkSource(X, y, chunk_rows=700))
    _trees_equal(m_frame._spec, m_chunk._spec)
    assert type(m_frame) is type(m_chunk)


def test_parquet_chunk_source_roundtrip(tmp_path, data):
    """frame/io.py's ParquetChunkSource streams the same rows the
    materialized reader would, and fits bit-identically to them."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    from sml_tpu.frame.io import read_parquet_chunks
    X, y = data
    cols = {f"f{i}": X[:, i] for i in range(X.shape[1])}
    cols["label"] = y
    # two part files, like a partitioned write
    half = len(X) // 2
    d = tmp_path / "part"
    d.mkdir()
    for i, sl in enumerate((slice(None, half), slice(half, None))):
        pq.write_table(pa.table({k: v[sl] for k, v in cols.items()}),
                       str(d / f"part-{i:05d}.parquet"))
    src = read_parquet_chunks(str(d), [f"f{i}" for i in range(X.shape[1])],
                              "label", chunkRows=512)
    Xs = np.concatenate([c[0] for c in src.chunks()])
    assert np.array_equal(Xs, X)
    assert src.n_rows == len(X)
    assert src.fingerprint() is not None
    spec_p = fit_ensemble_chunked(src, max_depth=3, max_bins=16, n_trees=2,
                                  bootstrap=True, seed=4)
    spec_m = _fit_ensemble(X, y, categorical={}, max_depth=3, max_bins=16,
                           min_instances=1, min_info_gain=0.0, n_trees=2,
                           feature_k=None, bootstrap=True, subsample=1.0,
                           seed=4, loss="squared")
    _trees_equal(spec_m, spec_p)


# ------------------------------------------------------------------- sketch
def test_sketch_compressed_edges_within_one_bin_width():
    """Past the exact cap the sketch compresses to weight-uniform
    centroids; quantile error stays under one bin width for
    sketchBuckets >> maxBins."""
    rng = np.random.default_rng(5)
    vals = rng.normal(size=50_000)
    sk = FeatureSketch(buckets=2048, exact_cap=10_000)
    for i in range(0, vals.size, 1000):
        sk.update(vals[i:i + 1000])
    assert not sk.exact and sk.compressions > 0
    probs = np.linspace(0, 1, 33)[1:-1]
    approx = sk.quantiles(probs)
    exact = np.quantile(vals, probs)
    assert np.abs(approx - exact).max() < np.diff(exact).max()


def test_sketch_merge_matches_single_stream():
    """Per-chunk sketches merged == one sketch over the whole stream
    (the mergeable-summary contract, exact mode bit-for-bit)."""
    rng = np.random.default_rng(6)
    X = rng.normal(size=(4000, 3))
    whole = DatasetSketch(3)
    whole.update(X)
    merged = DatasetSketch(3)
    for i in range(0, 4000, 256):
        part = DatasetSketch(3)
        part.update(X[i:i + 256])
        merged.merge(part)
    probs = np.linspace(0, 1, 17)[1:-1]
    for f in range(3):
        assert np.array_equal(whole.features[f].quantiles(probs),
                              merged.features[f].quantiles(probs))


def test_row_uniforms_stateless_and_uniform():
    """Random access == streaming; distribution sane."""
    a = row_uniforms(9, 0, 10_000)
    b = np.concatenate([row_uniforms(9, s, 1000)
                        for s in range(0, 10_000, 1000)])
    assert np.array_equal(a, b)
    assert 0.0 <= a.min() and a.max() < 1.0
    assert abs(a.mean() - 0.5) < 0.02


# ------------------------------------------------- prefetch + observability
def test_prefetch_overlap_event_ordering(data, recorder_on):
    """Chunk i+1's ingest.dispatch lands BEFORE chunk i's ingest.drain:
    the next chunk's host quantization + H2D genuinely overlaps the
    current chunk's device work (the PR-4 inference proof, for ingest)."""
    obs = recorder_on
    X, y = data
    GLOBAL_CONF.set("sml.data.prefetchChunks", 3)
    try:
        ingest_source(ArrayChunkSource(X, y, chunk_rows=256), 16)
    finally:
        GLOBAL_CONF.unset("sml.data.prefetchChunks")
    evs = [(e.name, e.args.get("chunk")) for e in obs.RECORDER.events()
           if e.name in ("ingest.dispatch", "ingest.drain")]
    first_drain = evs.index(("ingest.drain", 0))
    ahead = {c for name, c in evs[:first_drain]
             if name == "ingest.dispatch"}
    assert {0, 1, 2} <= ahead  # depth=3: three dispatches before drain 0
    # per-chunk walls land SKEW-style attribution: the slowest chunk is
    # NAMED in engine_health()'s ingest block
    health = obs.engine_health()
    assert health["ingest"] is not None
    assert health["ingest"]["n_devices"] >= 2  # lanes = chunk indices
    assert "slowest_device" in health["ingest"]


def test_ledger_bounded_residency(recorder_on):
    """The acceptance contract: fit end-to-end from a ChunkSource with
    device residency bounded by the COMPACT representation — peak
    chunk_stage + bin_cache delta ≪ the raw float bytes the source
    produced."""
    obs = recorder_on
    rng = np.random.default_rng(8)
    n, F = 200_000, 10
    raw_bytes = n * F * 8  # float64 raw chunks

    def make(start, stop):
        r = np.random.default_rng(start + 1)
        Xc = r.normal(size=(stop - start, F))
        return Xc, Xc[:, 0] + r.normal(0, 0.1, stop - start)

    src = GeneratorChunkSource(n, F, make, chunk_rows=16_384,
                               fingerprint=("ledger-test", n))
    led_before = obs.LEDGER.snapshot()
    bin_live_before = led_before.get("bin_cache", {}).get("live", 0)
    spec = fit_ensemble_chunked(src, max_depth=3, max_bins=32, n_trees=2,
                                bootstrap=True, seed=3)
    led = obs.LEDGER.snapshot()
    chunk_peak = led.get("chunk_stage", {}).get("peak", 0)
    bin_delta = led.get("bin_cache", {}).get("peak", 0) - bin_live_before
    assert chunk_peak > 0                      # the pool was exercised
    assert led["chunk_stage"]["live"] == 0     # and fully released
    # uint8 compact (1/8 of raw) + a few replicated chunk blocks: far
    # below raw float residency
    assert chunk_peak + bin_delta < raw_bytes / 3
    assert len(spec.trees) == 2
    rec = obs.RECORDER.counters()
    assert rec.get("ingest.raw_bytes", 0) >= raw_bytes  # SAW it all


def test_bin_cache_reuse_across_ingests(data, recorder_on):
    """Second fit on the same source: the ingest memo skips both passes,
    and the assembled device matrix is served from the bin cache (LRU
    hit, zero fresh chunk H2D)."""
    obs = recorder_on
    X, y = data
    src = ArrayChunkSource(X, y, chunk_rows=512)
    fit_ensemble_chunked(src, max_depth=3, max_bins=16, n_trees=2,
                         bootstrap=True, seed=3)
    c0 = obs.RECORDER.counters()
    fit_ensemble_chunked(src, max_depth=3, max_bins=16, n_trees=2,
                         bootstrap=True, seed=3)
    c1 = obs.RECORDER.counters()
    assert c1.get("ingest.memo_hit", 0) == c0.get("ingest.memo_hit", 0) + 1
    # no new chunk transfers; the fit's stage_sharded hit the bin cache
    assert c1.get("ingest.h2d_bytes", 0) == c0.get("ingest.h2d_bytes", 0)
    assert c1.get("staging.bin_cache_hit", 0) \
        > c0.get("staging.bin_cache_hit", 0)


def test_unlabeled_source_rejected_for_fit(data):
    X, _ = data
    with pytest.raises(ValueError, match="labeled"):
        fit_ensemble_chunked(ArrayChunkSource(X, chunk_rows=500),
                             max_depth=2, max_bins=8)


def test_pipeline_abandonment_releases_tickets_and_drains(recorder_on):
    """A caller abandoning the pipeline mid-stream (break / gen.close)
    must not leak watchdog tickets or in-flight resources: every
    dispatched item still gets its drain, and no ticket is left to rot
    into a false stall."""
    from sml_tpu.obs import WATCHDOG
    from sml_tpu.parallel.pipeline import prefetch_pipeline

    dispatched, drained = [], []
    gen = prefetch_pipeline(
        range(6), lambda x: x,
        lambda i, p: dispatched.append(i) or p,
        lambda i, h: drained.append(i) or h,
        depth=3, family="ingest", index_key="chunk")
    next(gen)    # one result out; more items in flight at depth=3
    gen.close()  # abandon
    assert WATCHDOG.report()["open"] == 0
    assert set(drained) == set(dispatched)  # cleanup drained the rest


# -------------------------------------------------------- regression sentry
def test_regress_scale_block_rules():
    """obs/regress.py: a vanished `scale` block is coverage regression
    (sidecar candidates only — driver records are exempt), rows/s drops
    flag at the capped tolerance, and a lost overlap-event proof flags."""
    from sml_tpu.obs import regress

    def sidecar(scale):
        return regress.normalize({"legs": {}, "metrics": {},
                                  "scale": scale})

    base_block = {
        "rows": 10_000_000, "ingest_rows_per_s": 300_000.0,
        "predict_rows_per_s": 400_000.0,
        "prefetch": {"events_ok": True},
    }
    base = sidecar(base_block)
    # identical candidate: clean
    assert regress.compare(base, sidecar(dict(base_block)))["ok"]
    # block vanished from a sidecar: coverage regression
    res = regress.compare(base, sidecar(None))
    assert not res["ok"]
    assert any(f["kind"] == "missing-scale-block"
               for f in res["regressions"])
    # driver records can never carry the block: exempt
    rec = regress.normalize({"parsed": {}, "tail": ""})
    assert regress.compare(base, rec)["ok"]
    # ingest throughput dropped 30% (> capped 18% tolerance): flags
    slow = dict(base_block, ingest_rows_per_s=210_000.0)
    res = regress.compare(base, sidecar(slow))
    assert any(f["kind"] == "scale-throughput"
               and f["key"] == "ingest_rows_per_s"
               for f in res["regressions"])
    # overlap proof vanished: the double buffer degraded to serial
    serial = dict(base_block, prefetch={"events_ok": False})
    res = regress.compare(base, sidecar(serial))
    assert any(f["kind"] == "scale-overlap" for f in res["regressions"])
    # different row counts are not comparable: no throughput judgment
    other = dict(base_block, rows=1_000_000,
                 ingest_rows_per_s=100_000.0)
    assert regress.compare(base, sidecar(other))["ok"]


# ------------------------------------------------------------- 1M-row smoke
def test_scale_smoke_1m_rows():
    """Tier-1-safe 1M-row synthetic smoke: chunked ingest + fit +
    streamed predict end-to-end from a generator source (raw data never
    materialized whole), compact device residency, finite outputs."""
    n, F = 1_000_000, 8

    def make(start, stop):
        r = np.random.default_rng(start * 7 + 5)
        Xc = r.normal(size=(stop - start, F)).astype(np.float32)
        yc = (Xc[:, 0] - 0.5 * Xc[:, 1] + r.normal(0, 0.3, stop - start)
              ).astype(np.float32)
        return Xc, yc

    src = GeneratorChunkSource(n, F, make, chunk_rows=131_072,
                               fingerprint=("smoke-1m", n))
    spec = fit_ensemble_chunked(src, max_depth=3, max_bins=32, n_trees=1,
                                seed=2)
    assert len(spec.trees) == 1
    # streamed predict on a 100k prefix regenerated from the same seeds
    psrc = GeneratorChunkSource(131_072, F, make, chunk_rows=131_072,
                                fingerprint=("smoke-1m-p", n))
    preds = predict_chunked(spec, psrc)
    assert preds.shape == (131_072,)
    assert np.isfinite(preds).all()
