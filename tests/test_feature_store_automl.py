"""Feature store (ML 10) + AutoML (ML 09) end-to-end tests."""

import numpy as np
import pandas as pd
import pytest

from sml_tpu import tracking as mlflow
from sml_tpu.feature_store import (FeatureLookup, FeatureStoreClient,
                                   feature_table)
from sml_tpu.ml import Pipeline
from sml_tpu.ml.feature import VectorAssembler
from sml_tpu.ml.regression import LinearRegression


@pytest.fixture(autouse=True)
def iso_dirs(tmp_path, monkeypatch):
    mlflow.set_tracking_uri(str(tmp_path / "runs"))
    monkeypatch.setenv("SML_FEATURE_STORE_DIR", str(tmp_path / "fs"))
    yield
    while mlflow.active_run():
        mlflow.end_run()


def test_feature_table_lifecycle(spark, airbnb_pdf):
    fs = FeatureStoreClient()

    @feature_table
    def compute_features(df):
        return df.select("id", "bedrooms", "accommodates")

    df = spark.createDataFrame(airbnb_pdf)
    feats = compute_features(df)
    ft = fs.create_feature_table("airbnb_features", keys=["id"],
                                 features_df=feats,
                                 description="base features")
    assert ft.name == "airbnb_features"
    back = fs.read_table("airbnb_features").toPandas()
    assert len(back) == len(airbnb_pdf)
    assert set(back.columns) == {"id", "bedrooms", "accommodates"}

    # merge upsert: update a subset + add a column
    upd = spark.createDataFrame(pd.DataFrame(
        {"id": [0, 1], "bedrooms": [9.0, 9.0], "accommodates": [9.0, 9.0],
         "new_feat": [1.0, 2.0]}))
    fs.write_table("airbnb_features", upd, mode="merge")
    merged = fs.read_table("airbnb_features").toPandas()
    assert len(merged) == len(airbnb_pdf)
    assert merged.set_index("id").loc[0, "bedrooms"] == 9.0
    assert "new_feat" in merged.columns
    meta = fs.get_table("airbnb_features")
    assert meta.primary_keys == ["id"]


def test_training_set_log_and_score_batch(spark, airbnb_pdf):
    fs = FeatureStoreClient()
    df = spark.createDataFrame(airbnb_pdf)
    fs.create_table("features_all", primary_keys=["id"],
                    df=df.select("id", "bedrooms", "accommodates", "bathrooms"))
    label_df = df.select("id", "price")
    lookups = [FeatureLookup(table_name="features_all", lookup_key=["id"])]
    ts = fs.create_training_set(label_df, lookups, label="price",
                                exclude_columns=["id"])
    train_df = ts.load_df()
    assert set(train_df.columns) == {"price", "bedrooms", "accommodates",
                                     "bathrooms"}
    pipeline = Pipeline(stages=[
        VectorAssembler(inputCols=["bedrooms", "accommodates", "bathrooms"],
                        outputCol="features"),
        LinearRegression(labelCol="price")])
    model = pipeline.fit(train_df)
    with mlflow.start_run() as run:
        fs.log_model(model, "model", training_set=ts,
                     registered_model_name="fs-model")
    # score_batch joins features by key automatically
    scored = fs.score_batch(f"runs:/{run.info.run_id}/model",
                            label_df.select("id", "price"))
    out = scored.toPandas()
    assert "prediction" in out.columns
    assert np.isfinite(out["prediction"]).all()


def test_automl_regress(spark, airbnb_pdf):
    from sml_tpu import automl
    df = spark.createDataFrame(
        airbnb_pdf[["bedrooms", "accommodates", "room_type", "price"]])
    summary = automl.regress(df, target_col="price", primary_metric="rmse",
                             timeout_minutes=5, max_trials=3)
    assert len(summary.trials) == 3
    best = summary.best_trial
    assert best.mlflow_run_id
    assert best.metrics["val_rmse"] > 0
    # best trial's model is loadable and scores
    model = mlflow.spark.load_model(f"runs:/{best.mlflow_run_id}/model")
    pred = model.transform(df).toPandas()
    assert "prediction" in pred.columns
    # rmse better than predicting the mean
    base = float(airbnb_pdf["price"].std())
    assert best.metrics["val_rmse"] < base
