"""Multi-replica serving fleet (sml_tpu/fleet — ISSUE 15).

Acceptance pins:
- per-replica queue attribution: each replica's admissions land on ITS
  `QueuePressure`, chained into the process-wide DEVICE_QUEUE;
- priority admission: the class ladder sheds lowest-first under
  pressure, the top class preempts the shed order (degrades through
  the endpoint ladder instead of shedding at the router);
- chaos: a replica killed mid-load drains its in-flight requests
  (re-route or shed — never a hung future), dumps a per-replica
  black-box bundle, and the autoscaler backfills;
- staged rollout: a clean candidate promotes replica-by-replica; an
  injected-divergence candidate auto-rolls-back, archives, and evicts
  the diverging replica with its bundle; a promotion landing
  mid-rollout aborts the rollout cleanly (the race test);
- the ContinuousTrainer promotes through the fleet rollout when
  constructed with `fleet=`.
"""

import os
import threading

import numpy as np
import pandas as pd
import pytest

import sml_tpu.tracking as mlflow
from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.ct import CanaryGate
from sml_tpu.fleet import Autoscaler, ReplicaPool, Router
from sml_tpu.ml import DeviceScorer, Pipeline
from sml_tpu.ml.feature import VectorAssembler
from sml_tpu.ml.regression import LinearRegression
from sml_tpu.serving import RequestShed
from sml_tpu.tracking import _store
from sml_tpu.utils.profiler import PROFILER


@pytest.fixture(autouse=True)
def tracking_dir(tmp_path):
    mlflow.set_tracking_uri(str(tmp_path / "runs"))
    mlflow.set_experiment("Default")
    yield
    while mlflow.active_run():
        mlflow.end_run()


@pytest.fixture(autouse=True)
def profiler_on():
    old = GLOBAL_CONF.get("sml.profiler.enabled")
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    yield
    GLOBAL_CONF.set("sml.profiler.enabled", old)


@pytest.fixture()
def obs_on(tmp_path):
    import sml_tpu.obs as obs
    old = GLOBAL_CONF.get("sml.obs.enabled")
    old_bb = GLOBAL_CONF.get("sml.obs.blackboxDir")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.obs.blackboxDir", str(tmp_path / "blackbox"))
    obs.reset()
    yield
    GLOBAL_CONF.set("sml.obs.enabled", old)
    GLOBAL_CONF.set("sml.obs.blackboxDir", old_bb)
    obs.reset()


def _counter(name):
    return PROFILER.counters().get(name, 0.0)


def _fit_linear(spark, seed=0, slope=2.0):
    rng = np.random.default_rng(seed)
    pdf = pd.DataFrame({"a": rng.normal(size=500),
                        "b": rng.normal(size=500)})
    pdf["y"] = slope * pdf["a"] - pdf["b"] + 1.0 \
        + rng.normal(0, 0.1, len(pdf))
    va = VectorAssembler(inputCols=["a", "b"], outputCol="features")
    return Pipeline(stages=[va, LinearRegression(labelCol="y")]) \
        .fit(spark.createDataFrame(pdf))


def _register(name, *models):
    for m in models:
        with mlflow.start_run():
            mlflow.spark.log_model(m, "model", registered_model_name=name)
    _store.set_version_stage(name, 1, "Production")


def _probe(seed=7, rows=8):
    return np.random.default_rng(seed).normal(size=(rows, 2)) \
        .astype(np.float32)


# --------------------------------------------------- queue attribution
def test_per_replica_queue_attribution(spark):
    """Each replica's admissions land on ITS QueuePressure; the
    process-wide DEVICE_QUEUE still sees the aggregate."""
    from sml_tpu.parallel import dispatch
    _register("fleet-attr", _fit_linear(spark))
    with ReplicaPool("fleet-attr", replicas=2, start=False,
                     timeout_millis=0) as pool:
        r0, r1 = pool.replicas()
        base = dispatch.DEVICE_QUEUE.rows()
        f = r0.endpoint.submit(_probe(rows=5))
        assert r0.pressure() == 5 and r1.pressure() == 0
        assert dispatch.DEVICE_QUEUE.rows() == base + 5
        g = r1.endpoint.submit(_probe(rows=3))
        assert r0.pressure() == 5 and r1.pressure() == 3
        assert dispatch.DEVICE_QUEUE.rows() == base + 8
        for r in (r0, r1):
            r.endpoint._batcher.start()
        f.result(30), g.result(30)
        assert r0.pressure() == 0 and r1.pressure() == 0
        assert dispatch.DEVICE_QUEUE.rows() == base


# --------------------------------------------------- priority admission
def test_priority_shed_ladder_low_sheds_first(spark):
    """Class i of n admits to (n-i)/n of the queue bound: low sheds
    first, normal next, and high preempts the shed order — past every
    bound it lands on the endpoint's own ladder (host fallback off →
    reason-tagged overflow shed)."""
    _register("fleet-ladder", _fit_linear(spark))
    with ReplicaPool("fleet-ladder", replicas=1, start=False,
                     queue_rows=30, host_fallback=False,
                     timeout_millis=0) as pool:
        router = Router(pool, priorities=["high", "normal", "low"])
        X = _probe(rows=5)
        ok = []
        # low admits to 10 rows, then sheds
        ok += [router.submit(X, "low") for _ in range(2)]
        shed_low = router.submit(X, "low")
        with pytest.raises(RequestShed):
            shed_low.result(1)
        # normal still admits (to 20 rows), then sheds
        ok += [router.submit(X, "normal") for _ in range(2)]
        with pytest.raises(RequestShed):
            router.submit(X, "normal").result(1)
        # high still admits (to 30 rows)
        ok += [router.submit(X, "high") for _ in range(2)]
        # ...and past the full bound it PREEMPTS: the endpoint's ladder
        # decides (host fallback off → batcher overflow shed)
        over0 = _counter("serve.shed.overflow")
        with pytest.raises(RequestShed):
            router.submit(X, "high").result(1)
        assert _counter("serve.shed.overflow") == over0 + 1
        assert _counter("fleet.shed.low") >= 1
        assert _counter("fleet.shed.normal") >= 1
        assert _counter("fleet.shed.high") == 0
        pool.replicas()[0].endpoint._batcher.start()
        for f in ok:
            assert f.result(30).shape == (5,)  # admitted traffic served


# --------------------------------------------------------------- chaos
def test_kill_replica_mid_load_reroutes_never_hangs(spark, obs_on,
                                                    tmp_path):
    """Kill a replica with requests in flight: every future resolves
    (re-routed onto the live replica — never a hung ScoreFuture), the
    evicted replica's black-box bundle is on disk, and the autoscaler
    backfills the pool to its floor."""
    m = _fit_linear(spark)
    _register("fleet-kill", m)
    expected = DeviceScorer(m).score_block(_probe(rows=2))
    bb_dir = str(tmp_path / "fleet-bb")
    with ReplicaPool("fleet-kill", replicas=2, start=False,
                     timeout_millis=0, blackbox_dir=bb_dir) as pool:
        router = Router(pool)
        futs = [router.submit(_probe(rows=2)) for _ in range(6)]
        on_dead = [f for f in futs if f.replica_id == 0]
        assert on_dead, "router never routed to replica 0"
        reroutes0 = _counter("fleet.reroutes")
        bundle = pool.kill(0)
        assert bundle is not None and os.path.isdir(bundle)
        assert os.path.isfile(os.path.join(bundle, "MANIFEST.json"))
        # the survivor's worker comes up; every future must resolve
        pool.get(1).endpoint._batcher.start()
        for f in futs:
            np.testing.assert_allclose(f.result(30), expected, rtol=1e-5)
        assert _counter("fleet.reroutes") - reroutes0 == len(on_dead)
        for f in on_dead:
            assert f.replica_id == 1  # re-routed onto the survivor
        # the pool fell under its floor: the autoscaler backfills
        assert pool.size() == 1
        asc = Autoscaler(pool, router, min_replicas=2, max_replicas=3)
        assert asc.step()["action"] == "backfill"
        assert pool.size() == 2


def test_autoscaler_occupancy_bands(spark):
    """Router-observed occupancy above the up-band adds a replica;
    an idle fleet at the down-band retires one (never below the
    floor)."""
    _register("fleet-bands", _fit_linear(spark))
    with ReplicaPool("fleet-bands", replicas=1, start=False,
                     queue_rows=20, timeout_millis=0) as pool:
        router = Router(pool)
        asc = Autoscaler(pool, router, min_replicas=1, max_replicas=2,
                         scale_up_occupancy=0.5,
                         scale_down_occupancy=0.2)
        futs = [router.submit(_probe(rows=4), "high") for _ in range(4)]
        up = asc.step()   # mean observed occupancy crossed 0.5
        assert up["action"] == "up" and pool.size() == 2
        for r in pool.replicas():
            r.endpoint._batcher.start()
        for f in futs:
            f.result(30)
        down = asc.step()  # no admissions since: instantaneous idle
        assert down["action"] == "down" and pool.size() == 1
        assert asc.step()["action"] == "hold"  # never below the floor


# ------------------------------------------------------- staged rollout
def test_staged_rollout_promotes_clean_candidate(spark, obs_on):
    """A near-identical candidate passes every per-replica gate stage,
    the alias commits once, and every replica converges unpinned."""
    m1 = _fit_linear(spark, seed=0, slope=2.0)
    m2 = _fit_linear(spark, seed=0, slope=2.0)  # same data: ~0 diff
    _register("fleet-clean", m1, m2)
    _store.set_version_stage("fleet-clean", 2, "Staging")
    with ReplicaPool("fleet-clean", replicas=2, canary_fraction=1.0,
                     flush_micros=500) as pool:
        gate = CanaryGate(min_mirrored=2, timeout_s=20.0,
                          max_abs_diff=0.2, batch_rows=2)
        v = pool.promote(2, gate=gate, X=_probe(rows=6))
        assert v["passed"] and v["action"] == "promoted"
        assert [s["passed"] for s in v["stages"]] == [True, True]
        assert _counter("fleet.rollout_promotions") >= 1
        for r in pool.replicas():
            assert r.endpoint.current_version() == 2
            assert r.endpoint.pinned_version() is None
    assert _store.resolve_stage("fleet-clean", "Production")["version"] \
        == 2
    assert _store.get_model_version("fleet-clean", 1)["current_stage"] \
        == "Archived"


def test_staged_rollout_rolls_back_on_divergence_and_evicts(
        spark, obs_on, tmp_path):
    """Injected divergence (a candidate trained on a flipped target)
    fails the first gate stage: the rollout rolls back, archives the
    candidate, and evicts the diverging replica with its per-replica
    black-box bundle — Production never moves."""
    m1 = _fit_linear(spark, seed=0, slope=2.0)
    m2 = _fit_linear(spark, seed=1, slope=-3.0)  # diverges hard
    _register("fleet-diverge", m1, m2)
    _store.set_version_stage("fleet-diverge", 2, "Staging")
    bb_dir = str(tmp_path / "rollout-bb")
    with ReplicaPool("fleet-diverge", replicas=2, canary_fraction=1.0,
                     flush_micros=500, blackbox_dir=bb_dir) as pool:
        gate = CanaryGate(min_mirrored=2, timeout_s=20.0,
                          max_abs_diff=0.05, batch_rows=2)
        v = pool.promote(2, gate=gate, X=_probe(rows=6))
        assert v["passed"] is False and v["action"] == "rolled_back"
        assert v["checks"]["divergence"] is False
        assert v["aborted_by_transition"] is False
        assert v["evicted"] == 0  # the replica whose gate failed
        assert v["blackbox"] and os.path.isdir(v["blackbox"])
        assert pool.get(0) is None and pool.size() == 1
        for r in pool.replicas():
            assert r.endpoint.current_version() == 1
            assert r.endpoint.pinned_version() is None
        assert _counter("fleet.rollout_rollbacks") >= 1
    assert _store.resolve_stage("fleet-diverge", "Production")["version"] \
        == 1
    assert _store.get_model_version("fleet-diverge", 2)["current_stage"] \
        == "Archived"


def test_promote_during_rollout_race_aborts_cleanly(spark, obs_on):
    """A promotion landing mid-rollout (the Production alias moves
    underneath) aborts the rollout down the rollback edge WITHOUT an
    eviction (nothing diverged): the fleet converges to whatever the
    alias now names, and the candidate archives only because it still
    held Staging."""
    m = _fit_linear(spark, seed=0, slope=2.0)
    _register("fleet-race", m, _fit_linear(spark, seed=0, slope=2.0),
              _fit_linear(spark, seed=0, slope=2.0))
    _store.set_version_stage("fleet-race", 2, "Staging")

    class RaceGate(CanaryGate):
        """Passes, but lands a competing v3 promotion right after the
        first stage's gate traffic — the alias check must catch it."""

        def run(self, endpoint, X, y, cand, inc):
            verdict = super().run(endpoint, X, y, cand, inc)
            _store.set_version_stage("fleet-race", 3, "Production",
                                     archive_existing_versions=True)
            return verdict

    with ReplicaPool("fleet-race", replicas=2, canary_fraction=1.0,
                     flush_micros=500) as pool:
        gate = RaceGate(min_mirrored=2, timeout_s=20.0, batch_rows=2)
        v = pool.promote(2, gate=gate, X=_probe(rows=6))
        assert v["passed"] is False and v["action"] == "rolled_back"
        assert v["aborted_by_transition"] is True
        assert v["evicted"] is None and v["blackbox"] is None
        assert pool.size() == 2  # nothing evicted
        for r in pool.replicas():
            assert r.endpoint.current_version() == 3  # the race's winner
            assert r.endpoint.pinned_version() is None
    assert _store.resolve_stage("fleet-race", "Production")["version"] == 3
    assert _store.get_model_version("fleet-race", 2)["current_stage"] \
        == "Archived"


def test_concurrent_promotes_serialize_on_the_rollout_lock(spark, obs_on):
    """Two threads promoting different Staging candidates through the
    same pool SERIALIZE on the rollout lock: each rollout runs whole
    (stages never interleave), the fleet converges to the later
    winner's alias, nothing stays pinned, and exactly one version holds
    Production — never a torn fleet."""
    m = _fit_linear(spark, seed=0, slope=2.0)
    _register("fleet-dual", m, _fit_linear(spark, seed=0, slope=2.0),
              _fit_linear(spark, seed=0, slope=2.0))
    _store.set_version_stage("fleet-dual", 2, "Staging")
    _store.set_version_stage("fleet-dual", 3, "Staging")
    results, errors = {}, {}
    with ReplicaPool("fleet-dual", replicas=2, canary_fraction=1.0,
                     flush_micros=500) as pool:
        gate = CanaryGate(min_mirrored=1, timeout_s=20.0, batch_rows=2)

        def promote(version):
            try:
                results[version] = pool.promote(version, gate=gate,
                                                X=_probe(rows=4))
            except ValueError as e:  # candidate left Staging meanwhile
                errors[version] = e

        threads = [threading.Thread(target=promote, args=(v,))
                   for v in (2, 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors and len(results) == 2
        # serialized rollouts both complete whole; the fleet converges
        # on whichever committed LAST, and the other is archived
        final = _store.resolve_stage("fleet-dual", "Production")["version"]
        assert final in (2, 3)
        other = 2 if final == 3 else 3
        assert _store.get_model_version("fleet-dual", other)[
            "current_stage"] == "Archived"
        stages = {_store.get_model_version("fleet-dual", v)[
            "current_stage"] for v in (1, 2, 3)}
        assert sorted(stages) == ["Archived", "Production"]
        for r in pool.replicas():
            assert r.endpoint.current_version() == final
            assert r.endpoint.pinned_version() is None


# ------------------------------------------------------- health surface
def test_engine_health_fleet_block_and_shed_reasons(spark, obs_on):
    """engine_health() grows a `fleet` block (per-replica table,
    shed-by-class) and a `shed` block (reason-tagged serve.shed)."""
    from sml_tpu import obs
    _register("fleet-health", _fit_linear(spark))
    with ReplicaPool("fleet-health", replicas=2, start=False,
                     queue_rows=12, host_fallback=False,
                     timeout_millis=0) as pool:
        router = Router(pool, priorities=["high", "low"])
        # low admits to 1/2 of each replica's 12-row bound: one request
        # per replica fits, the third finds every class bound exhausted
        router.submit(_probe(rows=5), "low")
        router.submit(_probe(rows=5), "low")
        with pytest.raises(RequestShed):
            router.submit(_probe(rows=5), "low").result(1)
        health = obs.engine_health()
        fl = health["fleet"]
        assert fl is not None and fl["shed_by_class"]["low"] >= 1
        p = [b for b in fl["pools"] if b["name"] == "fleet-health"][0]
        assert p["size"] == 2 and len(p["replicas"]) == 2
        assert p["replicas"][0]["queue_rows"] == 5
        assert health["shed"]["total"] >= 0.0
        for r in pool.replicas():
            r.endpoint._batcher.start()
    # after the pool closes its report leaves the registry
    from sml_tpu.fleet import fleet_report
    rep = fleet_report()
    assert rep is None or all(b["name"] != "fleet-health"
                              for b in rep["pools"])


def test_replica_start_shares_warm_caches(spark, tmp_path):
    """Replica 2 lands on replica 1's warm program caches: the prewarm
    guard is claimed once per (manifest, mesh) and the shared-cache
    skip is counted."""
    from sml_tpu.parallel import prewarm
    prev_dir = GLOBAL_CONF.get("sml.compile.cacheDir")
    GLOBAL_CONF.set("sml.compile.cacheDir", str(tmp_path / "cache"))
    GLOBAL_CONF.set("sml.prewarm.enabled", True)
    ran = dict(prewarm._ran)
    prewarm._ran.clear()
    try:
        _register("fleet-warm", _fit_linear(spark))
        skip0 = _counter("prewarm.replica_skip")
        with ReplicaPool("fleet-warm", replicas=2,
                         flush_micros=500) as pool:
            assert pool.size() == 2
            assert prewarm._ran.get(prewarm._guard_key()) is True
            assert _counter("prewarm.replica_skip") == skip0 + 1
    finally:
        GLOBAL_CONF.unset("sml.prewarm.enabled")
        GLOBAL_CONF.set("sml.compile.cacheDir", prev_dir or "")
        prewarm._ran.clear()
        prewarm._ran.update(ran)


# ------------------------------------------------- continuous training
def test_ct_trainer_promotes_through_fleet(spark, tmp_path, obs_on):
    """ContinuousTrainer(fleet=pool): a drifted window's warm refit
    promotes through the STAGED FLEET ROLLOUT — every replica gated,
    pinned, then converged on the committed alias."""
    from sml_tpu.ct import ContinuousTrainer, DeltaChunkSource
    from sml_tpu.frame._chunks import ArrayChunkSource
    from sml_tpu.ml._chunked import fit_ensemble_chunked
    from sml_tpu.ml.regression import GBTRegressionModel

    F = 6
    cols = [f"f{i}" for i in range(F)]

    def data(n, seed, shift=False):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, F))
        if shift:
            X[:, 0] += 1.5
            X[:, 2] *= 2.0
        y = (2.0 * X[:, 0] + 0.5 * X[:, 2] - X[:, 1] ** 2
             + rng.normal(0, 0.2, n)).astype(np.float32)
        return X, y

    Xt, yt = data(2400, seed=11)
    spec = fit_ensemble_chunked(
        ArrayChunkSource(Xt, yt, chunk_rows=700), categorical={},
        max_depth=3, max_bins=16, n_trees=6, seed=7, loss="squared",
        step_size=0.3, boosting=True)
    assert spec.baseline is not None
    with mlflow.start_run():
        mlflow.spark.log_model(GBTRegressionModel(spec), "model",
                               registered_model_name="fleet-ct")
    _store.set_version_stage("fleet-ct", 1, "Production")

    dpath = str(tmp_path / "stream")
    Xs, ys = data(900, seed=22, shift=True)
    pdf = pd.DataFrame({c: Xs[:, i] for i, c in enumerate(cols)})
    pdf["y"] = ys.astype(float)
    spark.createDataFrame(pdf).write.format("delta") \
        .mode("errorifexists").save(dpath)

    with ReplicaPool("fleet-ct", replicas=2, canary_fraction=1.0,
                     flush_micros=500) as pool:
        trainer = ContinuousTrainer(
            "fleet-ct", DeltaChunkSource(dpath, cols, "y"),
            fleet=pool,
            gate=CanaryGate(min_mirrored=3, timeout_s=20.0,
                            quality_tol=1.2, batch_rows=64),
            fit_params={"seed": 7, "rounds_per_dispatch": 2},
            warm_rounds=3, min_rows=512, full_severity=1e9)
        rep = trainer.step()
        assert rep["action"] == "promoted", rep
        assert rep["refit"] == "warm"
        gate = rep["gate"]
        assert gate["passed"] and gate["action"] == "promoted"
        assert len(gate["stages"]) == 2
        assert all(s["passed"] for s in gate["stages"])
        for r in pool.replicas():
            assert r.endpoint.current_version() == 2
            assert r.endpoint.pinned_version() is None
    assert _store.resolve_stage("fleet-ct", "Production")["version"] == 2
    assert _store.get_model_version("fleet-ct", 1)["current_stage"] \
        == "Archived"
    assert trainer.stats()["promotions"] == 1


# ----------------------------------------------------- regress guard
def _fleet_block(hung=0, up_ok=True, down_ok=True, clean=True,
                 rolled_back=True, bb=True, order=True, fanin=True,
                 low_shed=0.6, low_p99=50.0):
    return {
        "requests": 10_000,
        "hung_futures": hung,
        "priority_order_ok": order,
        "priority": {
            "high": {"p99_ms": 20.0, "shed_rate": 0.0},
            "normal": {"p99_ms": 30.0, "shed_rate": 0.2},
            "low": {"p99_ms": low_p99, "shed_rate": low_shed},
        },
        "scale": {"up_ok": up_ok, "down_ok": down_ok},
        "rollout": {"clean": {"passed": clean},
                    "rollback": {"rolled_back": rolled_back,
                                 "blackbox_on_disk": bb}},
        "trace": {"fanin_ok": fanin},
    }


def _sidecar(block):
    doc = {"legs": {}, "value": 1.0, "metrics": {}}
    if block is not None:
        doc["fleet"] = block
    return doc


def test_regress_guards_fleet_proofs():
    from sml_tpu.obs import regress
    base = regress.normalize(_sidecar(_fleet_block()))
    assert regress.compare(base, base)["ok"]
    # vanished block = coverage regression (sidecar candidates only)
    r = regress.compare(base, regress.normalize(_sidecar(None)))
    assert any(f["kind"] == "missing-fleet-block"
               for f in r["regressions"])

    def bad(**kw):
        return regress.compare(
            base, regress.normalize(_sidecar(_fleet_block(**kw))))

    assert any(f["kind"] == "fleet-liveness"
               for f in bad(hung=3)["regressions"])
    for kw, key in ((dict(rolled_back=False),
                     "rollout.rollback.rolled_back"),
                    (dict(bb=False), "rollout.rollback.blackbox_on_disk"),
                    (dict(clean=False), "rollout.clean.passed"),
                    (dict(up_ok=False), "scale.up_ok"),
                    (dict(down_ok=False), "scale.down_ok"),
                    (dict(order=False), "priority_order_ok"),
                    (dict(fanin=False), "trace.fanin_ok")):
        r = bad(**kw)
        assert any(f["kind"] == "fleet-proof" and f["key"] == key
                   for f in r["regressions"]), key
    # load numbers: p99 at the serving tolerance, shed rate noise-aware
    assert any(f["kind"] == "fleet-latency"
               for f in bad(low_p99=200.0)["regressions"])
    assert any(f["kind"] == "fleet-shed-rate"
               for f in bad(low_shed=0.95)["regressions"])
    # the committed sidecar's fleet block self-compares clean
    committed = regress.load("bench_legs.json")
    assert committed.get("fleet") is not None
    assert regress.compare(committed, committed)["ok"]


# --------------------------------------------------- shed reason tags
def test_deadline_shed_counts_reason(spark):
    """The deadline shed path is reason-tagged next to the total."""
    import time

    from sml_tpu.serving import MicroBatcher
    m = _fit_linear(spark)
    scorer = DeviceScorer(m)
    b = MicroBatcher(scorer.score_block, max_batch_rows=16,
                     timeout_millis=30, flush_micros=1000, start=False)
    futs = [b.submit(_probe(rows=1)) for _ in range(3)]
    time.sleep(0.1)
    d0 = _counter("serve.shed.deadline")
    b.start()
    for f in futs:
        with pytest.raises(RequestShed):
            f.result(30)
    b.close()
    assert _counter("serve.shed.deadline") == d0 + 3
