import numpy as np
import pandas as pd
import pytest

import sml_tpu.frame.functions as F


def test_range_and_count(spark):
    df = spark.range(1000)
    assert df.count() == 1000
    assert df.columns == ["id"]
    assert df.rdd.getNumPartitions() >= 1


def test_select_withcolumn_filter(spark):
    df = spark.range(100)
    out = (df.withColumn("x", F.col("id") * 2)
             .withColumn("y", F.col("x") + 1)
             .filter(F.col("id") < 10)
             .select("id", "y"))
    pdf = out.toPandas()
    assert len(pdf) == 10
    assert list(pdf["y"]) == [i * 2 + 1 for i in range(10)]


def test_when_otherwise_translate_cast(spark):
    pdf = pd.DataFrame({"price": ["$1,200.00", "$85.00", "$3.50"]})
    df = spark.createDataFrame(pdf)
    out = df.withColumn("price_d", F.translate(F.col("price"), "$,", "").cast("double"))
    vals = out.toPandas()["price_d"].tolist()
    assert vals == [1200.0, 85.0, 3.5]

    df2 = spark.createDataFrame(pd.DataFrame({"n": [1.0, 5.0, 10.0]}))
    out2 = df2.withColumn("cls", F.when(F.col("n") > 6, "high")
                          .when(F.col("n") > 2, "mid").otherwise("low"))
    assert out2.toPandas()["cls"].tolist() == ["low", "mid", "high"]


def test_groupby_agg(spark):
    pdf = pd.DataFrame({"k": ["a", "b", "a", "b", "a"], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    df = spark.createDataFrame(pdf)
    out = df.groupBy("k").agg(F.avg("v").alias("m"), F.count("*").alias("c")).orderBy("k")
    res = out.toPandas()
    assert res["m"].tolist() == [3.0, 3.0]
    assert res["c"].tolist() == [3, 2]


def test_groupby_count(spark, airbnb_df):
    out = airbnb_df.groupBy("room_type").count().orderBy(F.col("count").desc())
    res = out.toPandas()
    assert res["count"].sum() == 2000
    assert res["count"].iloc[0] >= res["count"].iloc[-1]


def test_orderby_limit(spark, airbnb_df):
    top = airbnb_df.orderBy(F.col("price").desc()).limit(5).toPandas()
    all_prices = airbnb_df.toPandas()["price"]
    assert top["price"].iloc[0] == all_prices.max()
    assert len(top) == 5


def test_random_split_seeded_deterministic(spark, airbnb_df):
    a1, b1 = airbnb_df.randomSplit([0.8, 0.2], seed=42)
    a2, b2 = airbnb_df.randomSplit([0.8, 0.2], seed=42)
    assert a1.count() == a2.count()
    assert b1.count() == b2.count()
    assert a1.count() + b1.count() == 2000
    # roughly 80/20
    assert 0.7 < a1.count() / 2000 < 0.9


def test_random_split_partition_dependence(spark, airbnb_pdf):
    """The ML 02:38-52 lesson: same seed, different partition layout ⇒
    different membership."""
    df8 = spark.createDataFrame(airbnb_pdf, numPartitions=8)
    df2 = spark.createDataFrame(airbnb_pdf, numPartitions=2)
    a8, _ = df8.randomSplit([0.8, 0.2], seed=42)
    a2, _ = df2.randomSplit([0.8, 0.2], seed=42)
    ids8 = set(a8.toPandas()["id"])
    ids2 = set(a2.toPandas()["id"])
    assert ids8 != ids2  # partition-dependent, as documented


def test_dropduplicates_union_join(spark):
    pdf = pd.DataFrame({"k": [1, 2, 2, 3], "v": ["a", "b", "b", "c"]})
    df = spark.createDataFrame(pdf)
    assert df.dropDuplicates().count() == 3
    assert df.union(df).count() == 8
    right = spark.createDataFrame(pd.DataFrame({"k": [1, 2], "w": [10.0, 20.0]}))
    j = df.dropDuplicates().join(right, on="k", how="inner").orderBy("k").toPandas()
    assert j["w"].tolist() == [10.0, 20.0]
    anti = df.dropDuplicates().join(right, on="k", how="left_anti").toPandas()
    assert anti["k"].tolist() == [3]


def test_describe_summary_quantile(spark, airbnb_df):
    d = airbnb_df.describe("price").toPandas()
    assert d["summary"].tolist() == ["count", "mean", "stddev", "min", "max"]
    assert float(d["price"][0]) == 2000
    s = airbnb_df.select("price").summary().toPandas()
    assert "50%" in s["summary"].tolist()
    q = airbnb_df.approxQuantile("price", [0.5], 0.01)
    assert q[0] > 0


def test_repartition_coalesce(spark):
    df = spark.range(100)
    assert df.repartition(10).rdd.getNumPartitions() == 10
    assert df.repartition(10).coalesce(3).rdd.getNumPartitions() == 3
    assert df.repartition(10).count() == 100
    byk = df.withColumn("k", F.col("id") % 4).repartition(4, "k")
    assert byk.count() == 100


def test_monotonic_id_and_partition_id(spark):
    df = spark.range(100, numPartitions=4).withColumn("mid", F.monotonically_increasing_id())
    pdf = df.toPandas()
    assert pdf["mid"].is_unique
    pids = spark.range(100, numPartitions=4).select(F.spark_partition_id().alias("p")).toPandas()
    assert set(pids["p"]) == {0, 1, 2, 3}


def test_rand_seeded(spark):
    df = spark.range(50, numPartitions=2)
    a = df.withColumn("r", F.rand(seed=1)).toPandas()["r"]
    b = df.withColumn("r", F.rand(seed=1)).toPandas()["r"]
    assert np.allclose(a, b)
    assert a.between(0, 1).all()


def test_temp_view_sql(spark, airbnb_df):
    airbnb_df.createOrReplaceTempView("listings")
    out = spark.sql("SELECT room_type, COUNT(*) AS n FROM listings GROUP BY room_type ORDER BY n DESC")
    pdf = out.toPandas()
    assert pdf["n"].sum() == 2000


def test_filter_string_expr(spark, airbnb_df):
    assert airbnb_df.filter("bedrooms >= 2 AND price > 100").count() > 0


def test_na_functions(spark):
    pdf = pd.DataFrame({"a": [1.0, None, 3.0], "b": ["x", "y", None]})
    df = spark.createDataFrame(pdf)
    assert df.na.drop().count() == 1
    assert df.na.drop(subset=["a"]).count() == 2
    filled = df.na.fill(0.0).toPandas()
    assert filled["a"].tolist() == [1.0, 0.0, 3.0]


def test_cache_and_lazy(spark):
    df = spark.range(10).withColumn("x", F.col("id") + 1)
    assert df._parts is None  # lazy until an action
    df.cache()
    assert df._parts is not None


def test_collect_rows(spark):
    rows = spark.range(3).collect()
    assert [r.id for r in rows] == [0, 1, 2]
    assert rows[0]["id"] == 0
    assert rows[0].asDict() == {"id": 0}


def test_csv_roundtrip(spark, airbnb_pdf, tmp_path):
    p = str(tmp_path / "listings_csv")
    spark.createDataFrame(airbnb_pdf).write.option("header", True).csv(p)
    back = spark.read.csv(p, header=True, inferSchema=True)
    assert back.count() == 2000
    assert "price" in back.columns


def test_parquet_roundtrip_partitions(spark, airbnb_pdf, tmp_path):
    p = str(tmp_path / "listings_pq")
    spark.createDataFrame(airbnb_pdf, numPartitions=8).write.mode("overwrite").parquet(p)
    back = spark.read.parquet(p)
    assert back.count() == 2000
    assert back.rdd.getNumPartitions() == 8  # one part-file per partition


def test_null_group_key(spark):
    pdf = pd.DataFrame({"k": ["a", None, "a"], "v": [1.0, 2.0, 3.0]})
    out = spark.createDataFrame(pdf).groupBy("k").agg(F.sum("v").alias("s")).toPandas()
    assert len(out) == 2 and out["s"].sum() == 6.0


def test_union_positional(spark):
    a = spark.createDataFrame(pd.DataFrame({"x": [1]}))
    b = spark.createDataFrame(pd.DataFrame({"y": [2]}))
    assert a.union(b).toPandas()["x"].tolist() == [1, 2]


def test_case_when_null_then_value(spark):
    pdf = pd.DataFrame({"a": [1.0, -1.0], "b": [None, None]})
    out = spark.createDataFrame(pdf).withColumn(
        "c", F.when(F.col("a") > 0, F.col("b")).otherwise(F.lit("OTH"))).toPandas()
    assert out["c"].tolist() == [None, "OTH"]


def test_boolean_cast_strings(spark):
    pdf = pd.DataFrame({"s": ["true", "false", "junk"]})
    out = spark.createDataFrame(pdf).withColumn("b", F.col("s").cast("boolean")).toPandas()
    assert out["b"].tolist() == [True, False, None]


def test_head_empty(spark):
    assert spark.createDataFrame(pd.DataFrame({"a": []})).head() is None


def test_partitioned_append(spark, tmp_path):
    p = str(tmp_path / "papp")
    spark.createDataFrame(pd.DataFrame({"k": [1], "v": [1.0]})) \
        .write.partitionBy("k").mode("overwrite").parquet(p)
    spark.createDataFrame(pd.DataFrame({"k": [1], "v": [2.0]})) \
        .write.partitionBy("k").mode("append").parquet(p)
    assert spark.read.parquet(p).count() == 2


def test_sql_view_materialization_is_cached(spark, airbnb_pdf, monkeypatch):
    """Repeated SQL over the same view loads it into the session store ONCE;
    re-registering the view invalidates (VERDICT r2 weak #7)."""
    from sml_tpu.frame import sql as sqlmod
    calls = []
    orig = sqlmod._to_sqlite

    def counting(pdf, name, con):
        calls.append(name)
        return orig(pdf, name, con)

    monkeypatch.setattr(sqlmod, "_to_sqlite", counting)
    df = spark.createDataFrame(airbnb_pdf)
    df.createOrReplaceTempView("cached_view")
    n1 = spark.sql("SELECT count(*) AS n FROM cached_view").toPandas()
    n2 = spark.sql("SELECT avg(price) AS p FROM cached_view").toPandas()
    assert calls.count("cached_view") == 1  # one load serves both queries
    assert int(n1["n"].iloc[0]) == len(airbnb_pdf)
    # replacing the view re-materializes
    df2 = spark.createDataFrame(airbnb_pdf.iloc[:100])
    df2.createOrReplaceTempView("cached_view")
    n3 = spark.sql("SELECT count(*) AS n FROM cached_view").toPandas()
    assert int(n3["n"].iloc[0]) == 100
    assert calls.count("cached_view") == 2


def test_sql_dropped_view_errors_not_stale(spark, airbnb_pdf):
    """Dropping a view must invalidate the session SQL store — a query on
    the dropped name errors instead of returning the stale copy."""
    import pandas.errors
    df = spark.createDataFrame(airbnb_pdf)
    df.createOrReplaceTempView("doomed_view")
    assert spark.sql("SELECT count(*) n FROM doomed_view").toPandas() is not None
    spark.catalog.dropTempView("doomed_view")
    with pytest.raises((pandas.errors.DatabaseError, Exception)):
        spark.sql("SELECT count(*) n FROM doomed_view").toPandas()


def test_tail(spark):
    df = spark.createDataFrame(pd.DataFrame({"x": list(range(10))}))
    rows = df.tail(3)
    assert [r["x"] for r in rows] == [7, 8, 9]
    assert len(df.tail(99)) == 10


def test_shuffle_reuse_cache_and_unpersist(spark):
    """applyInPandas memoizes the group split of a cached frame; a
    mutating fn cannot pollute it; unpersist drops the entries; the byte
    bound refuses oversized splits."""
    import pandas as pd
    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.frame import grouped as G

    pdf = pd.DataFrame({"k": ["a", "b", "c"] * 400,
                        "v": np.arange(1200, dtype=float)})
    df = spark.createDataFrame(pdf)
    df.cache()
    df.toPandas()

    def fn(g):
        g["v"] = -1.0  # hostile in-place mutation
        return pd.DataFrame({"k": [g["k"].iloc[0]], "n": [len(g)]})

    sch = "k string, n bigint"
    r1 = df.groupby("k").applyInPandas(fn, sch).toPandas()
    with G._split_lock:
        assert any(v[0] is df.__dict__["_pdf_cache"]
                   for v in G._split_cache.values())
    r2 = df.groupby("k").applyInPandas(fn, sch).toPandas()
    assert sorted(r1["n"]) == sorted(r2["n"]) == [400, 400, 400]
    assert float(df.toPandas()["v"].min()) >= 0  # source unpolluted
    token = df.__dict__["_pdf_cache"]
    df.unpersist()
    with G._split_lock:
        assert not any(v[0] is token for v in G._split_cache.values())

    # byte bound: a 0 budget refuses to cache at all
    old = GLOBAL_CONF.get("sml.shuffle.reuseBytes")
    GLOBAL_CONF.set("sml.shuffle.reuseBytes", 0)
    try:
        df2 = spark.createDataFrame(pdf)
        df2.cache()
        df2.toPandas()
        df2.groupby("k").applyInPandas(fn, sch).toPandas()
        tok2 = df2.__dict__["_pdf_cache"]
        with G._split_lock:
            assert not any(v[0] is tok2 for v in G._split_cache.values())
    finally:
        GLOBAL_CONF.set("sml.shuffle.reuseBytes", old)
