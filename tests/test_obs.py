"""Flight recorder (sml_tpu.obs): event bus, Chrome-trace export,
dispatch audit, HBM memory ledger, run autologging, and the
disabled-path overhead contract (PR 2 tentpole + acceptance criteria).
"""

import json
import time

import numpy as np
import pandas as pd
import pytest

from sml_tpu import obs
from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.parallel import dispatch
from sml_tpu.parallel.dispatch import WorkHint
from sml_tpu.utils.profiler import PROFILER


@pytest.fixture()
def recorder():
    """Recorder + profiler on, clean state; everything restored after."""
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    PROFILER.reset()
    obs.reset()
    try:
        yield obs.RECORDER
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", False)
        GLOBAL_CONF.set("sml.profiler.enabled", False)
        GLOBAL_CONF.set("sml.obs.sinkPath", "")
        GLOBAL_CONF.set("sml.obs.ringEvents", 65536)
        GLOBAL_CONF.set("sml.obs.sinkMaxBytes", 64 << 20)
        PROFILER.reset()
        obs.reset()


def _fresh_frame(spark, n=4000, seed=None):
    """Unique-content frame so staging-cache misses are guaranteed (the
    content-keyed caches survive across tests in one process)."""
    rng = np.random.default_rng(np.random.SeedSequence(seed))
    pdf = pd.DataFrame({
        "k": rng.choice(["a", "b", "c"], n, p=[0.8, 0.1, 0.1]),
        "x1": rng.normal(size=n), "x2": rng.normal(size=n),
    })
    pdf["label"] = pdf["x1"] * 2 + rng.normal(size=n)
    return spark.createDataFrame(pdf)


def _fit_and_shuffle(spark):
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    df = _fresh_frame(spark)
    df.groupBy("k").count().toPandas()
    Pipeline(stages=[
        VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
        LinearRegression(labelCol="label")]).fit(df)
    return df


# ------------------------------------------------------- chrome trace export
def test_chrome_trace_roundtrip(spark, recorder, tmp_path):
    """Acceptance: a Pipeline fit + groupBy shuffle exports a trace that
    json.loads with >= 4 distinct tracks (host ops, device programs,
    >= 2 counter tracks), well-formed ph/ts/dur/pid/tid fields, properly
    stacked nested spans, and monotonic byte-volume counter tracks."""
    _fit_and_shuffle(spark)
    path = str(tmp_path / "trace.json")
    assert obs.export_chrome_trace(path) == path
    doc = json.load(open(path))
    evs = doc["traceEvents"]

    spans = [e for e in evs if e["ph"] == "X"]
    counters = [e for e in evs if e["ph"] == "C"]
    assert spans and counters
    for e in spans:
        assert {"ph", "ts", "dur", "pid", "tid", "name"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] >= 0
    for e in counters:
        assert {"ph", "ts", "pid", "tid", "name", "args"} <= set(e)

    # >= 4 distinct tracks: host-op thread lanes + the virtual device
    # track + counter tracks
    span_tracks = {(e["pid"], e["tid"]) for e in spans}
    counter_tracks = {e["name"] for e in counters}
    host_tracks = {t for t in span_tracks if t[0] == 1}
    device_tracks = {t for t in span_tracks if t[0] == 2}
    assert host_tracks, "no host-op track"
    assert device_tracks, "no device-program track"
    assert len(counter_tracks) >= 2, counter_tracks
    assert len(span_tracks) + len(counter_tracks) >= 4
    # dispatched programs (and only those) ride the device track
    assert all(e["name"].startswith("program.")
               for e in spans if e["pid"] == 2)

    # nested spans stack: within a lane, spans are disjoint or contained
    for track in span_tracks:
        lane = sorted((e for e in spans if (e["pid"], e["tid"]) == track),
                      key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        eps = 50.0  # us: perf_counter rounding slack
        for e in lane:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] - eps:
                stack.pop()
            if stack:
                assert e["ts"] + e["dur"] <= \
                    stack[-1]["ts"] + stack[-1]["dur"] + eps, \
                    (e, stack[-1])
            stack.append(e)

    # byte-volume counter tracks are cumulative => monotone nondecreasing
    for name in ("staging.h2d_bytes", "staging.d2h_bytes"):
        vals = [e["args"]["value"] for e in counters if e["name"] == name]
        assert vals, f"missing counter track {name}"
        assert vals == sorted(vals), name

    # a nested-span pair actually exists (materialize chains nest)
    host_lane = [e for e in spans if e["pid"] == 1]
    nested = any(
        a is not b and a["ts"] <= b["ts"]
        and b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 50.0
        for a in host_lane for b in host_lane)
    assert nested, "expected at least one nested host span pair"


# ------------------------------------------------------------ dispatch audit
def test_audit_lists_dispatches_with_predictions(spark, recorder):
    """Acceptance: after a fit, audit_report() lists every dispatch with
    predicted host/device times, and program spans attach measured wall
    times."""
    _fit_and_shuffle(spark)
    recs = obs.audit_records()
    assert recs, "no dispatch decisions recorded"
    for r in recs:
        assert r.route in ("host", "device")
        assert r.t_host >= 0 and r.t_device >= 0
        assert r.kind
    assert any(r.measured is not None for r in recs)
    report = obs.audit_report()
    assert "dispatch audit" in report
    assert "pred_host" in report and "measured" in report
    assert f"{len(recs)} decisions" in report


@pytest.fixture
def tunneled(monkeypatch):
    """Pinned fake tunnel calibration (as in test_dispatch.py)."""
    monkeypatch.setattr(dispatch, "_default_backend", lambda: "tpu")
    cal = dispatch._Calibration()
    cal._done = True
    cal.rt_fixed = 0.15
    cal.h2d_bw = 200e6
    cal.d2h_bw = 20e6
    monkeypatch.setattr(dispatch, "CALIBRATION", cal)
    yield cal


def test_forced_device_misroute_flagged(recorder, tunneled):
    """Satellite: sml.dispatch.mode=device on tiny work must surface a
    predicted-vs-actual inversion in the audit — the forced device route
    measured far slower than the host prediction."""
    GLOBAL_CONF.set("sml.dispatch.mode", "device")
    try:
        route, _ = dispatch.decide(WorkHint(flops=1e6, kind="blas"))
        assert route == "device"
        with PROFILER.span("program.tiny", route="device"):
            time.sleep(0.02)
    finally:
        GLOBAL_CONF.set("sml.dispatch.mode", "auto")
    rec = obs.audit_records()[-1]
    assert rec.forced and rec.reason == "forced-mode"
    assert rec.route == "device"
    assert rec.measured is not None and rec.measured >= 0.02
    assert rec.t_host < rec.t_device  # the model would have said host
    assert rec.misroute
    report = obs.audit_report()
    assert "MISROUTE" in report and "predicted-inversion" in report


def test_probe_decisions_are_not_double_counted(recorder, tunneled,
                                                monkeypatch):
    """_route_mesh prices with internal decide() probes; the audit must
    count DISPATCHES, not probes — exactly one row per routed program."""
    from sml_tpu.ml import _staging
    monkeypatch.setattr(dispatch, "OBSERVED_HOST", dispatch._ObservedRates())
    # resident device loses outright -> the early host fast path
    obs._audit.reset()
    _mesh, route = _staging._route_mesh(WorkHint(flops=1e8, kind="blas"), ())
    assert route == "host"
    recs = obs.audit_records()
    assert len(recs) == 1, [(r.route, r.forced) for r in recs]
    assert recs[0].route == "host" and not recs[0].forced
    # resident device wins but the H2D charge flips it -> the priced path
    obs._audit.reset()
    X = np.random.default_rng(3).normal(size=(4096, 64)).astype(np.float32)
    tunneled.h2d_bw = 1e6
    _mesh, route = _staging._route_mesh(WorkHint(flops=5e9, kind="blas"),
                                        (X,), may_promote=False)
    assert route == "host"
    recs = obs.audit_records()
    assert len(recs) == 1, [(r.route, r.forced) for r in recs]
    assert recs[0].route == "host" and not recs[0].forced


def test_uncalibrated_forced_route_does_not_calibrate(recorder, monkeypatch):
    """audit_preroute on a forced route must not trigger the tunnel
    calibration probe (observability must not change engine behavior);
    the uncalibrated record is marked and exempt from host-side misroute
    judgment."""
    monkeypatch.setattr(dispatch, "_default_backend", lambda: "tpu")
    cal = dispatch._Calibration()   # NOT done: ensure() would measure
    monkeypatch.setattr(dispatch, "CALIBRATION", cal)
    GLOBAL_CONF.set("sml.dispatch.mode", "host")
    try:
        route, _ = dispatch.decide(WorkHint(flops=1e6, kind="blas"))
    finally:
        GLOBAL_CONF.set("sml.dispatch.mode", "auto")
    assert route == "host"
    assert not cal._done, "audit must not have run the calibration probe"
    rec = obs.audit_records()[-1]
    assert rec.forced and not rec.calibrated
    rec.measured = 10.0  # even a huge wall can't flag an unjudgeable row
    assert not rec.misroute


def test_audit_not_recorded_when_disabled(tunneled):
    GLOBAL_CONF.set("sml.obs.enabled", False)
    assert not obs.RECORDER.enabled
    obs._audit.reset()
    dispatch.decide(WorkHint(flops=1e12, kind="blas"))
    assert obs.audit_records() == []


# ------------------------------------------------------------- memory ledger
def test_memory_ledger_tracks_pools(spark, recorder):
    """A tree fit allocates into the bin cache; the ledger's live/peak
    bytes and memory_report() surface it."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import DecisionTreeRegressor
    df = _fresh_frame(spark, seed=None)
    before = obs.LEDGER.snapshot().get("bin_cache", {"live": 0})["live"]
    Pipeline(stages=[
        VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
        DecisionTreeRegressor(labelCol="label", maxDepth=3, maxBins=16),
    ]).fit(df)
    snap = obs.LEDGER.snapshot()
    assert snap["bin_cache"]["live"] > before
    assert snap["bin_cache"]["peak"] >= snap["bin_cache"]["live"]
    assert snap["_total"]["peak"] >= snap["bin_cache"]["peak"]
    report = obs.memory_report()
    assert "bin_cache" in report and "TOTAL" in report
    # the exporter got hbm counter-track events for the allocation
    assert any(e.name == "hbm.bin_cache_bytes"
               for e in obs.RECORDER.events())


def test_ledger_alloc_free_and_peaks():
    obs.LEDGER.alloc("boost_margin", 1000)
    obs.LEDGER.alloc("boost_margin", 500)
    obs.LEDGER.free("boost_margin", 1500)
    snap = obs.LEDGER.snapshot()["boost_margin"]
    assert snap["live"] == 0 and snap["peak"] >= 1500
    obs.LEDGER.reset_peaks()
    assert obs.LEDGER.snapshot()["boost_margin"]["peak"] == 0


# ----------------------------------------------------- ring + sink mechanics
def test_tid_map_bounded_under_short_lived_threads(recorder):
    """Satellite: the thread-id -> dense-tid map must not grow forever
    under serving's short-lived client threads — past _MAX_TIDS, dead
    threads' slots are reclaimed and reused."""
    import threading

    from sml_tpu.obs._recorder import _MAX_TIDS

    def emit_once(i):
        obs.RECORDER.emit("cache", "cache.test", args={"i": i})

    for i in range(_MAX_TIDS + 90):
        t = threading.Thread(target=emit_once, args=(i,))
        t.start()
        t.join()
    assert len(obs.RECORDER._tids) <= _MAX_TIDS + 1, \
        "dead-thread tid slots leaked"
    # reclaimed lanes stay DENSE: no tid ever exceeded the bound
    tids = {e.tid for e in obs.RECORDER.events()
            if e.name == "cache.test"}
    assert max(tids) < _MAX_TIDS + 1
    # and the newest emits were recorded (reuse, not refusal)
    seen = {e.args["i"] for e in obs.RECORDER.events()
            if e.name == "cache.test"}
    assert _MAX_TIDS + 89 in seen


def test_ring_is_bounded_and_counts_drops(recorder):
    GLOBAL_CONF.set("sml.obs.ringEvents", 32)
    for i in range(100):
        obs.RECORDER.emit("cache", "cache.test", args={"i": i})
    evs = obs.RECORDER.events()
    assert len(evs) == 32
    assert evs[-1].args["i"] == 99  # newest survive
    assert obs.RECORDER.dropped >= 68


def test_jsonl_sink_writes_events(recorder, tmp_path):
    sink = tmp_path / "events.jsonl"
    GLOBAL_CONF.set("sml.obs.sinkPath", str(sink))
    PROFILER.count("staging.cache_hit")
    with PROFILER.span("program.sink_test", route="host"):
        pass
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert lines
    kinds = {ln["kind"] for ln in lines}
    assert "counter" in kinds and "span" in kinds
    assert all("ts" in ln and "name" in ln for ln in lines)


def test_jsonl_sink_rotates_once_at_byte_bound(recorder, tmp_path):
    """Satellite: past sml.obs.sinkMaxBytes the live file rolls to
    <path>.1 (replacing the previous roll) and reopens fresh — the sink
    is bounded at ~2x the knob instead of growing without limit, and
    rotation never splits a record."""
    sink = tmp_path / "events.jsonl"
    GLOBAL_CONF.set("sml.obs.sinkPath", str(sink))
    GLOBAL_CONF.set("sml.obs.sinkMaxBytes", 4096)
    for i in range(400):
        obs.RECORDER.emit("cache", "cache.rotate_test", args={"i": i})
    rolled = tmp_path / "events.jsonl.1"
    assert rolled.exists(), "no rotation happened"
    assert sink.stat().st_size < 4096 + 512  # live file re-bounded
    # every line in BOTH files is a complete JSON record, and the live
    # file continues the sequence the roll left off at
    seen = []
    for path in (rolled, sink):
        for ln in path.read_text().splitlines():
            rec = json.loads(ln)
            if rec["name"] == "cache.rotate_test":
                seen.append(rec["args"]["i"])
    assert seen == sorted(seen)
    assert seen[-1] == 399
    # ~2x bound: at most bound bytes per file (+ one record of slack)
    assert rolled.stat().st_size <= 4096 + 512


def test_jsonl_sink_rotation_preserves_line_atomicity(recorder, tmp_path):
    """Satellite: concurrent emitters across a rotation never interleave
    or tear a line — writes and the roll both happen under the emit
    lock."""
    import threading
    sink = tmp_path / "events.jsonl"
    GLOBAL_CONF.set("sml.obs.sinkPath", str(sink))
    GLOBAL_CONF.set("sml.obs.sinkMaxBytes", 2048)

    def emitter(tid):
        for i in range(150):
            obs.RECORDER.emit("cache", "cache.rotate_test",
                              args={"t": tid, "i": i, "pad": "x" * 40})

    threads = [threading.Thread(target=emitter, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = 0
    for path in (tmp_path / "events.jsonl.1", sink):
        if not path.exists():
            continue
        for ln in path.read_text().splitlines():
            rec = json.loads(ln)  # raises on a torn/interleaved line
            if rec["name"] == "cache.rotate_test":
                total += 1
    # both surviving files parse cleanly; with a single rotation the
    # oldest roll may be gone, but what is on disk is never torn
    assert total > 0


# ------------------------------------------------- disabled-path overhead
def test_disabled_recorder_costs_one_attribute_load():
    """Satellite + acceptance: with sml.obs.enabled=false the
    instrumentation is within noise of free — the ring records nothing,
    and per-event cost stays microscopic (generous bound: the actual
    cost is ~1us; the bound only guards against an accidental conf
    lookup or lock acquisition landing on the hot path)."""
    GLOBAL_CONF.set("sml.obs.enabled", False)
    GLOBAL_CONF.set("sml.profiler.enabled", False)
    assert not obs.RECORDER.enabled
    obs.RECORDER.reset()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        PROFILER.count("staging.cache_hit")
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 20e-6, f"{per_event * 1e6:.2f}us per disabled event"
    assert obs.RECORDER.events() == []
    assert obs.RECORDER.counters() == {}
    # spans: same contract
    t0 = time.perf_counter()
    for _ in range(n):
        with PROFILER.span("program.noop"):
            pass
    per_span = (time.perf_counter() - t0) / n
    assert per_span < 30e-6, f"{per_span * 1e6:.2f}us per disabled span"
    assert obs.RECORDER.events() == []
    # streaming metrics registry (PR 7): same contract — recording into a
    # disabled registry is a no-op with no histogram allocation
    obs.METRICS.reset()
    t0 = time.perf_counter()
    for _ in range(n):
        obs.METRICS.observe("serve.request_ms", 1.5)
    per_obs = (time.perf_counter() - t0) / n
    assert per_obs < 20e-6, f"{per_obs * 1e6:.2f}us per disabled observe"
    assert obs.METRICS.names() == []
    assert obs.METRICS.histogram("serve.request_ms") is None
    # skew hooks (PR 7): a disabled note() allocates nothing either
    obs.SKEW.reset()
    profile = [0.01] * 8
    t0 = time.perf_counter()
    for _ in range(2000):
        obs.SKEW.note("program.noop", profile)
    per_note = (time.perf_counter() - t0) / 2000
    assert per_note < 20e-6, f"{per_note * 1e6:.2f}us per disabled note"
    assert obs.SKEW.programs() == []
    assert obs.straggler_report() is None
    # trace context (PR 8): disabled current()/mint/fan_in return None
    # behind one attribute load — no ContextVar read, no allocation
    from sml_tpu.obs import _context
    t0 = time.perf_counter()
    for _ in range(n):
        _context.current()
    per_ctx = (time.perf_counter() - t0) / n
    assert per_ctx < 20e-6, f"{per_ctx * 1e6:.2f}us per disabled current"
    assert _context.current() is None
    assert _context.mint_request(rows=1) is None
    assert _context.fan_in([]) is None
    assert obs.RECORDER.events() == []  # mint emitted nothing
    # stall watchdog (PR 8): disabled open() registers nothing, starts
    # no thread, and costs one attribute load
    t0 = time.perf_counter()
    for _ in range(n):
        obs.WATCHDOG.open("dispatch", "program.noop")
    per_open = (time.perf_counter() - t0) / n
    assert per_open < 20e-6, f"{per_open * 1e6:.2f}us per disabled open"
    assert obs.WATCHDOG.report()["open"] == 0
    # exemplar-carrying observe: same disabled contract as plain observe
    t0 = time.perf_counter()
    for _ in range(n):
        obs.METRICS.observe("serve.request_ms", 1.5, exemplar=12345)
    per_ex = (time.perf_counter() - t0) / n
    assert per_ex < 20e-6, f"{per_ex * 1e6:.2f}us per disabled exemplar"
    assert obs.METRICS.histogram("serve.request_ms") is None


# -------------------------------------------------------- profiler reset fix
def test_profiler_reset_mid_span_invalidates_stack():
    """Satellite: a reset() while a span is open must not attribute later
    child time to the stale parent entry, and the straddling span itself
    must not be recorded (generation counter)."""
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    try:
        PROFILER.reset()
        with PROFILER.span("outer"):
            PROFILER.reset()  # fires while `outer` is open
            with PROFILER.span("child"):
                time.sleep(0.005)
        spans = {s.name: s for s in PROFILER.spans()}
        # the straddling span is dropped; the post-reset child is intact
        assert "outer" not in spans
        assert "child" in spans
        child = spans["child"]
        # the child's full wall time is its own (no stale parent absorbed
        # it, and no stale stack entry corrupted its self time)
        assert child.self_s == pytest.approx(child.wall_s)
        # a fresh span after the dust settles records normally
        with PROFILER.span("after"):
            pass
        assert any(s.name == "after" for s in PROFILER.spans())
    finally:
        GLOBAL_CONF.set("sml.profiler.enabled", False)
        PROFILER.reset()


# -------------------------------------------------------- tracking autolog
def test_fit_autologs_engine_metrics(spark, recorder, tmp_path):
    """Acceptance: a fit under an active tracking run logs >= 6 engine.*
    metrics retrievable from the file-based store."""
    from sml_tpu import tracking
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    tracking.set_tracking_uri(str(tmp_path / "runs"))
    df = _fresh_frame(spark)
    with tracking.start_run(run_name="obs-autolog") as run:
        Pipeline(stages=[
            VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
            LinearRegression(labelCol="label")]).fit(df)
    rec = tracking.get_run(run.info.run_id)
    eng = {k: v for k, v in rec.data.metrics.items()
           if k.startswith("engine.")}
    assert len(eng) >= 6, sorted(eng)
    assert eng["engine.h2d_bytes"] > 0
    assert 0.0 <= eng["engine.cache_hit_rate"] <= 1.0


def test_no_autolog_without_active_run(spark, recorder, tmp_path):
    from sml_tpu import tracking
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    tracking.set_tracking_uri(str(tmp_path / "runs"))
    df = _fresh_frame(spark)
    Pipeline(stages=[
        VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
        LinearRegression(labelCol="label")]).fit(df)
    exp = tracking._store.default_experiment()["experiment_id"]
    assert tracking._store.list_runs(exp) == []  # no implicit runs


def test_engine_metrics_shape(recorder):
    m = obs.engine_metrics()
    assert len(m) >= 6
    assert all(k.startswith("engine.") for k in m)
    assert all(isinstance(v, float) for v in m.values())
