"""Engine health layer (ISSUE 7): streaming metrics core, per-device
straggler attribution, engine_health() snapshot, and the bench_diff
perf-regression sentry CI gate.

Acceptance:
- log-bucketed p50/p99 land within ONE BUCKET WIDTH (2**(1/8)) of the
  exact sorted-sample computation at the same rank (the contract that
  let bench.py's raw-sort path be deleted);
- an injected 8-device skewed timing profile names the slow chip, the
  skew ratio matches the injected imbalance, and the report survives a
  Chrome-trace export round-trip;
- `engine_health()` is populated (metric quantiles, audit, ledger, SLO
  burn-rate) after a serving-shaped load;
- `scripts/bench_diff.py` self-compare on the committed artifacts exits
  0 with zero findings; a >=20% injected wall regression on any leg is
  flagged and exits non-zero.
"""

import json
import math
import subprocess
import sys
import os
import re
import time

import numpy as np
import pytest

from sml_tpu import obs
from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.obs._metrics import BUCKET_GROWTH, LogHistogram
from sml_tpu.obs._trace import PID_SKEW, to_trace_events

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
BENCH_DIFF = os.path.join(REPO, "scripts", "bench_diff.py")


@pytest.fixture()
def recorder():
    GLOBAL_CONF.set("sml.obs.enabled", True)
    obs.reset()
    try:
        yield obs.RECORDER
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", False)
        obs.reset()


# ------------------------------------------------------- metrics histograms
def _exact_quantile(samples, q):
    srt = np.sort(samples)
    rank = min(max(int(math.ceil(q * len(srt))), 1), len(srt))
    return float(srt[rank - 1])


def test_histogram_percentile_parity_with_exact_sort():
    """Satellite: the log-bucketed p50/p99 over a serving-leg-shaped
    latency sample lands within one bucket width of the exact
    sorted-sample quantile at the same rank — the precision contract
    that replaced bench.py's raw-sort percentile path."""
    rng = np.random.default_rng(42)
    # the bench serving leg's shape: ~2000 lognormal request latencies ms
    samples = np.exp(rng.normal(1.2, 0.9, 2000))
    h = LogHistogram()
    for s in samples:
        h.observe(float(s))
    for q in (0.50, 0.90, 0.99):
        exact = _exact_quantile(samples, q)
        got = h.quantile(q)
        assert got > 0
        ratio = got / exact
        assert 1.0 / BUCKET_GROWTH <= ratio <= BUCKET_GROWTH, \
            (q, exact, got, ratio)
    assert h.count == len(samples)
    assert h.max == pytest.approx(float(samples.max()))
    assert h.min == pytest.approx(float(samples.min()))
    # mean is exact (tracked as a true sum, not from buckets)
    snap = h.snapshot()
    assert snap["mean"] == pytest.approx(float(samples.mean()))


def test_histogram_snapshots_merge_by_bucket_addition():
    """Mergeable snapshots: two shards' histograms combine into the same
    quantiles as one histogram over the union."""
    rng = np.random.default_rng(3)
    a_s, b_s = rng.exponential(5.0, 800), rng.exponential(20.0, 400)
    ha, hb, hu = LogHistogram(), LogHistogram(), LogHistogram()
    for s in a_s:
        ha.observe(float(s))
        hu.observe(float(s))
    for s in b_s:
        hb.observe(float(s))
        hu.observe(float(s))
    merged = obs.merge_snapshots(ha.snapshot(), hb.snapshot())
    assert merged["count"] == 1200
    assert merged["p50"] == pytest.approx(hu.quantile(0.5))
    assert merged["p99"] == pytest.approx(hu.quantile(0.99))
    assert merged["mean"] == pytest.approx(hu.snapshot()["mean"])
    # object-level merge matches too
    ha.merge(hb)
    assert ha.count == 1200
    assert ha.quantile(0.99) == pytest.approx(hu.quantile(0.99))


def test_histogram_count_above_and_rate():
    h = LogHistogram(window_s=60.0)
    for v in (1.0, 2.0, 4.0, 100.0, 200.0):
        h.observe(v)
    assert h.total_count() == 5
    # threshold far from bucket edges: exactly the two large samples
    assert h.count_above(50.0) == 2
    assert h.count_above(0.001) == 5
    assert h.rate_per_s(60.0) >= 0.0


def test_registry_routes_through_recorder_flag(recorder):
    obs.METRICS.observe("serve.request_ms", 3.0)
    assert obs.METRICS.histogram("serve.request_ms").count == 1
    snap = obs.METRICS.snapshot()
    assert snap["serve.request_ms"]["count"] == 1


# --------------------------------------------------- straggler attribution
INJECTED = [0.010] * 7 + [0.030]  # device 7 is 3x the others


def test_straggler_report_names_slow_chip_and_matches_imbalance(recorder):
    """Satellite: an injected 8-device skewed profile — the report names
    the slow chip and the skew ratio matches the injected imbalance."""
    attr = obs.SKEW.note("fit_8dev", INJECTED, wall_s=0.040,
                         psum_bytes=123456.0, psum_launches=8)
    assert attr["slowest_device"] == 7
    expected_ratio = max(INJECTED) / (sum(INJECTED) / len(INJECTED))
    assert attr["skew_ratio"] == pytest.approx(expected_ratio, rel=1e-6)
    # BSP decomposition: 7 chips each wait (0.030 - 0.010)
    assert attr["wait_s"] == pytest.approx(7 * 0.020)
    assert attr["collective_overhead_s"] == pytest.approx(0.010)
    rep = obs.straggler_report()
    assert rep["slowest_device"] == 7
    assert rep["n_devices"] == 8
    assert rep["skew_ratio"] == pytest.approx(expected_ratio, rel=1e-4)
    assert rep["psum_bytes"] == 123456.0
    assert rep["psum_launches"] == 8
    # wait share: 7 * 0.02 wait vs 8 * 0.01 + 0.03 compute
    total_c, total_w = sum(INJECTED), 7 * 0.020
    assert rep["wait_share"] == pytest.approx(
        total_w / (total_c + total_w), abs=1e-3)


def test_straggler_report_stable_across_trace_roundtrip(recorder):
    """Satellite: export the ring as a Chrome trace, rebuild the report
    from the trace's skew lanes — same slow chip, same skew ratio."""
    obs.SKEW.note("fit_8dev", INJECTED)
    obs.SKEW.note("fit_8dev_round2", [c * 2 for c in INJECTED])
    live = obs.straggler_report()
    trace = to_trace_events(obs.RECORDER.events())
    rebuilt = obs.skew_report_from_trace(trace)
    assert rebuilt is not None
    assert rebuilt["slowest_device"] == live["slowest_device"]
    assert rebuilt["n_devices"] == live["n_devices"]
    assert rebuilt["skew_ratio"] == pytest.approx(live["skew_ratio"],
                                                  rel=1e-3)
    assert rebuilt["wait_share"] == pytest.approx(live["wait_share"],
                                                  abs=1e-3)


def test_trace_renders_one_lane_per_device(recorder):
    """Acceptance: the Chrome trace gains a per-device process (pid 3)
    with one named lane per chip, compute and wait spans disjoint within
    each lane."""
    obs.SKEW.note("fit_8dev", INJECTED)
    trace = to_trace_events(obs.RECORDER.events())
    lanes = {e["tid"] for e in trace
             if e.get("ph") == "X" and e["pid"] == PID_SKEW}
    assert lanes == set(range(8))
    names = {e["args"]["name"] for e in trace
             if e.get("ph") == "M" and e["pid"] == PID_SKEW
             and e["name"] == "thread_name"}
    assert "device-7" in names
    # within a lane, compute ends where wait begins (no overlap)
    for tid in lanes - {7}:  # device 7 has no wait span
        lane = [e for e in trace if e.get("ph") == "X"
                and e["pid"] == PID_SKEW and e["tid"] == tid]
        lane.sort(key=lambda e: e["ts"])
        assert len(lane) == 2
        assert lane[0]["name"] == "skew.compute"
        assert lane[1]["name"] == "skew.wait"
        assert lane[1]["ts"] == pytest.approx(
            lane[0]["ts"] + lane[0]["dur"], abs=1.0)


def test_skew_note_honors_real_device_ids(recorder):
    """The bench probe passes jax.Device.ids: the report and the trace
    lanes must indict the REAL chip, not the shard's row-order
    position (they differ on non-identity device assignments)."""
    attr = obs.SKEW.note("fit", [0.01, 0.09, 0.02], devices=[12, 7, 30])
    assert attr["slowest_device"] == 7
    rep = obs.straggler_report()
    assert rep["slowest_device"] == 7
    assert {d["device"] for d in rep["per_device"]} == {7, 12, 30}
    trace = to_trace_events(obs.RECORDER.events())
    lanes = {e["tid"] for e in trace
             if e.get("ph") == "X" and e["pid"] == PID_SKEW}
    assert lanes == {7, 12, 30}
    rebuilt = obs.skew_report_from_trace(trace)
    assert rebuilt["slowest_device"] == 7


def test_skew_note_noop_when_disabled():
    GLOBAL_CONF.set("sml.obs.enabled", False)
    obs.SKEW.reset()
    assert obs.SKEW.note("x", [1.0, 2.0]) is None
    assert obs.SKEW.programs() == []
    assert obs.straggler_report() is None


# ------------------------------------------------------------ engine health
def _drive_serving_load(n_requests=64):
    from sml_tpu.serving import MicroBatcher

    def score(X):
        time.sleep(0.0002)  # a visible, sub-SLO device cost
        return np.asarray(X).sum(axis=1)

    with MicroBatcher(score, max_batch_rows=32, flush_micros=200,
                      timeout_millis=0) as mb:
        futs = [mb.submit(np.ones((2, 4), np.float32))
                for _ in range(n_requests)]
        for f in futs:
            f.result(timeout=10)
    return n_requests


def test_engine_health_populated_after_serving_load(recorder):
    """Acceptance: after a serving-shaped load, engine_health() carries
    populated metric quantiles, the audit block, the HBM ledger, and the
    SLO burn-rate; the snapshot also lands a health.snapshot event."""
    n = _drive_serving_load()
    health = obs.engine_health()
    m = health["metrics"]["serve.request_ms"]
    assert m["count"] == n
    assert m["p50"] > 0 and m["p99"] >= m["p50"]
    assert health["slo"]["requests"] == n
    assert health["slo"]["target_ms"] == 250.0
    assert health["slo"]["burn_rate"] == 0.0  # sub-ms requests, 250ms SLO
    assert "decisions" in health["audit"]
    assert "dispatch audit" in health["audit"]["report"]
    assert "_total" in health["hbm"]
    assert health["engine"]["engine.cache_hit_rate"] >= 0.0
    assert any(e.name == "health.snapshot" and e.kind == "health"
               for e in obs.RECORDER.events())


def test_slo_burn_rate_counts_breaches(recorder):
    """A 1ms SLO against ~constant >=1ms latencies burns the budget: the
    breach fraction comes from the histogram's bucket-exact count."""
    GLOBAL_CONF.set("sml.serve.sloMillis", 1)
    try:
        for _ in range(100):
            obs.METRICS.observe("serve.request_ms", 50.0)
        slo = obs.slo_report()
    finally:
        GLOBAL_CONF.unset("sml.serve.sloMillis")
    assert slo["requests"] == 100
    assert slo["breaches"] == 100
    assert slo["breach_fraction"] == 1.0
    assert slo["burn_rate"] == pytest.approx(100.0)  # 100% over a 1% budget
    assert any(e.name == "slo.burn_rate" for e in obs.RECORDER.events())


def test_endpoint_latency_flows_into_dispatch_histograms(recorder):
    """The audit's measured-wall attach also feeds per-route dispatch
    histograms in the metrics core."""
    from sml_tpu.utils.profiler import PROFILER
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    try:
        with PROFILER.span("program.health_probe", route="host"):
            time.sleep(0.002)
    finally:
        GLOBAL_CONF.set("sml.profiler.enabled", False)
    h = obs.METRICS.histogram("dispatch.host_ms")
    assert h is not None and h.count >= 1
    assert h.quantile(0.5) >= 1.0  # >= ~2ms measured, one-bucket exact


# -------------------------------------------------------- regression sentry
def _run_diff(*args):
    return subprocess.run(
        [sys.executable, BENCH_DIFF, *args],
        capture_output=True, text=True, timeout=120, cwd=REPO)


def test_bench_diff_self_compare_committed_artifacts():
    """Satellite/acceptance: the committed BENCH_r01.json and the
    committed sidecar each self-compare to ZERO findings, exit 0 — and
    the gate runs jax-free (it is a tier-1 CI test)."""
    for artifact in ("BENCH_r01.json", "bench_legs.json"):
        proc = _run_diff(os.path.join(REPO, artifact), "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        result = json.loads(proc.stdout)
        assert result["ok"] is True
        assert result["regressions"] == []
        assert result["checked"] > 0


def test_bench_diff_flags_injected_sidecar_regression(tmp_path):
    """Acceptance: a >=20% injected wall regression on any sidecar leg is
    flagged and exits non-zero; engine-counter growth is flagged too."""
    with open(os.path.join(REPO, "bench_legs.json")) as f:
        doc = json.load(f)
    leg = doc["legs"]["ml07_cv"]
    leg["seconds"] = round(leg["seconds"] * 1.25, 3)
    leg["seconds_per_pass"] = [round(x * 1.25, 3)
                               for x in leg["seconds_per_pass"]]
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(doc))
    proc = _run_diff(os.path.join(REPO, "bench_legs.json"), str(cand),
                     "--json")
    assert proc.returncode == 1, proc.stdout
    result = json.loads(proc.stdout)
    keys = {f["key"] for f in result["regressions"]}
    assert "ml07_cv" in keys


def test_bench_diff_flags_injected_bench_record_regression(tmp_path):
    """The BENCH_r0x driver-record format is diffable too: a 30% slower
    leg in the tail flags."""
    with open(os.path.join(REPO, "BENCH_r01.json")) as f:
        doc = json.load(f)
    doc["tail"] = re.sub(
        r"ml11_xgb(\s+)([0-9.]+)s",
        lambda m: f"ml11_xgb{m.group(1)}{float(m.group(2)) * 1.3:.2f}s",
        doc["tail"])
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(doc))
    proc = _run_diff(os.path.join(REPO, "BENCH_r01.json"), str(cand),
                     "--json")
    assert proc.returncode == 1, proc.stdout
    result = json.loads(proc.stdout)
    assert any(f["key"] == "ml11_xgb" and f["kind"] == "leg-wall"
               for f in result["regressions"])


def test_bench_diff_counter_and_collective_and_coverage_rules(tmp_path):
    """The non-wall rules: a leg vanishing, a dispatch-count growth, and
    a multichip psum-payload growth each flag independently."""
    from sml_tpu.obs import regress
    base = regress.load(os.path.join(REPO, "bench_legs.json"))
    # a leg disappears -> coverage regression
    import copy
    cand = copy.deepcopy(base)
    cand["legs"].pop("ml06_dt")
    res = regress.compare(base, cand)
    assert any(f["kind"] == "missing-leg" and f["key"] == "ml06_dt"
               for f in res["regressions"])
    # tree-fit dispatch count grows -> fusion-contract regression (the
    # committed sidecar predates per-leg counters, so pin them on both
    # sides and grow the candidate's)
    base2 = copy.deepcopy(base)
    base2["legs"]["ml07_cv"]["counters"]["tree.fit_dispatch"] = 4.0
    cand = copy.deepcopy(base2)
    cand["legs"]["ml07_cv"]["counters"]["tree.fit_dispatch"] = 13.0
    res = regress.compare(base2, cand)
    assert any(f["kind"] == "leg-counter"
               and f["key"].endswith("tree.fit_dispatch")
               for f in res["regressions"])
    # multichip psum payload grows 10% -> collective-static regression
    with open(os.path.join(REPO, "bench_legs.json")) as f:
        raw = json.load(f)
    if raw.get("multichip"):
        cand_raw = copy.deepcopy(raw)
        for e in cand_raw["multichip"]["widths"]:
            e["collective_psum_bytes"] *= 1.10
        res = regress.compare(regress.normalize(raw),
                              regress.normalize(cand_raw))
        assert any(f["kind"] == "multichip-collective"
                   for f in res["regressions"])
    # kernel.fallback growth -> EXACT rule: growth by even 1 flags, and
    # a key ABSENT from the base leg counts as 0 (legs only record
    # counters that fired, so the realistic regression is 0 -> N with no
    # base key at all)
    cand = copy.deepcopy(base)
    assert "kernel.fallback" not in cand["legs"]["ml07_rf"]["counters"]
    cand["legs"]["ml07_rf"]["counters"]["kernel.fallback"] = 1.0
    res = regress.compare(base, cand)
    assert any(f["kind"] == "leg-counter"
               and f["key"].endswith("kernel.fallback")
               for f in res["regressions"])
    if raw.get("kernel"):
        cand_raw = copy.deepcopy(raw)
        for e in cand_raw["kernel"]["legs"]:
            e["kernel_counters"]["kernel.fallback"] += 1.0
        res = regress.compare(regress.normalize(raw),
                              regress.normalize(cand_raw))
        assert any(f["kind"] == "kernel-fallback"
                   for f in res["regressions"])
        # the kernelbench gate vanishing (or one sweep leg) is coverage
        # loss, same as an ordinary leg going missing
        cand_raw = copy.deepcopy(raw)
        cand_raw.pop("kernel")
        res = regress.compare(regress.normalize(raw),
                              regress.normalize(cand_raw))
        assert any(f["kind"] == "missing-kernel-block"
                   for f in res["regressions"])
        cand_raw = copy.deepcopy(raw)
        cand_raw["kernel"]["legs"] = cand_raw["kernel"]["legs"][1:]
        res = regress.compare(regress.normalize(raw),
                              regress.normalize(cand_raw))
        assert any(f["kind"] == "missing-kernel-leg"
                   for f in res["regressions"])
        # and the committed kernel block self-compares clean
        res0 = regress.compare(regress.normalize(raw),
                               regress.normalize(raw))
        assert res0["ok"]


def test_regress_verdicts_annotate_the_trace(recorder, tmp_path):
    """Verdicts land in the flight recorder as regress.verdict events
    and render as instant markers in the exported trace; bench_diff
    --trace writes the standalone marker file."""
    from sml_tpu.obs import regress
    base = regress.load(os.path.join(REPO, "bench_legs.json"))
    import copy
    cand = copy.deepcopy(base)
    cand["legs"]["ml02_lr"]["seconds"] *= 1.5
    cand["legs"]["ml02_lr"]["passes"] = [
        x * 1.5 for x in cand["legs"]["ml02_lr"]["passes"]]
    res = regress.compare(base, cand)
    assert not res["ok"]
    n = obs.annotate_regressions(res["regressions"])
    assert n == len(res["regressions"]) >= 1
    trace = to_trace_events(obs.RECORDER.events())
    marks = [e for e in trace if e.get("ph") == "i"
             and e["name"] == "regress.verdict"]
    assert len(marks) >= 1
    assert marks[0]["args"]["key"] == "ml02_lr"
    # the CLI's standalone trace file
    out = tmp_path / "verdicts.json"
    proc = _run_diff(os.path.join(REPO, "bench_legs.json"),
                     os.path.join(REPO, "bench_legs.json"),
                     "--trace", str(out))
    assert proc.returncode == 0
    doc = json.loads(out.read_text())
    assert "traceEvents" in doc
