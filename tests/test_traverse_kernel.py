"""Pallas fused tree-traversal inference kernel + autotuner (ISSUE 12).

The contract (docs/KERNELS.md): with `sml.infer.kernel=pallas` on a
non-TPU backend the traversal kernel runs in INTERPRET mode, op-for-op
`_forest_margin`'s math — kernel-path predictions must be BIT-IDENTICAL
to the XLA traversal for DT/RF/boosted ensembles across bin dtypes, NaN
rows, and the logistic finalize; 'auto' never emulates on CPU; the
resolved (kernel, block_rows) spec keys the program cache; autotuned
specs round-trip through the prewarm manifest; the VMEM guard demotes
oversized (block_rows × trees) specs on real TPU; and the fallback /
spec surface shows in `engine_health()["infer_kernel"]` and the
`obs/regress.py` kernel_infer rules.
"""

import json
import os
import types

import numpy as np
import pytest

from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.utils.profiler import PROFILER


@pytest.fixture()
def infer_conf():
    """Restore scoring-kernel knobs after each test."""
    keys = ("sml.infer.kernel", "sml.infer.kernelBlockRows",
            "sml.infer.autotune", "sml.profiler.enabled",
            "sml.dispatch.mode")
    prev = {k: GLOBAL_CONF.get(k) for k in keys}
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    GLOBAL_CONF.set("sml.infer.autotune", False)
    yield
    for k, v in prev.items():
        GLOBAL_CONF.set(k, v)


def _toy(n=5000, f=8, seed=0, nan_rows=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    if nan_rows:
        X[::17, 2] = np.nan  # binned like any other value by bin_with
    y = (2 * X[:, 0] - np.nan_to_num(X[:, 1]) ** 2
         + rng.normal(0, 0.3, n)).astype(np.float32)
    return X.astype(np.float32), y


def _fit_kind(kind, X, y, max_bins):
    from sml_tpu.ml._tree_models import _fit_ensemble
    common = dict(categorical={}, max_bins=max_bins, min_instances=1,
                  min_info_gain=0.0, seed=7)
    if kind == "dt":
        return _fit_ensemble(X, y, max_depth=5, n_trees=1, feature_k=None,
                             bootstrap=False, subsample=1.0,
                             loss="squared", **common)
    if kind == "rf":
        return _fit_ensemble(X, y, max_depth=4, n_trees=6, feature_k=3,
                             bootstrap=True, subsample=1.0,
                             loss="squared", **common)
    if kind == "xgb":
        return _fit_ensemble(X, y, max_depth=4, n_trees=5, feature_k=None,
                             bootstrap=False, subsample=1.0,
                             loss="squared", boosting=True,
                             reg_lambda=1.0, **common)
    raise AssertionError(kind)


def _margins(spec, binned, kernel):
    from sml_tpu.ml import inference
    GLOBAL_CONF.set("sml.infer.kernel", kernel)
    sf, sb, lv, w = spec.stacked()
    return inference.predict_forest_sharded(
        binned, sf, sb, lv, w, spec.depth, base=spec.base,
        n_bins=spec.binning.edges.shape[1] + 1)


# ------------------------------------------------------------ bit parity
@pytest.mark.parametrize("kind", ["dt", "rf", "xgb"])
@pytest.mark.parametrize("max_bins", [32, 300])  # uint8 / uint16 operands
def test_margin_parity_bitwise_vs_xla(spark, infer_conf, kind, max_bins):
    """Kernel-path margins == XLA-path margins, bit for bit, for every
    ensemble kind, both compact bin dtypes, NaN rows included."""
    from sml_tpu.ml import tree_impl
    X, y = _toy()
    spec = _fit_kind(kind, X, y, max_bins)
    binned = tree_impl.bin_with(np.asarray(X, np.float64), spec.binning)
    assert binned.dtype == (np.uint8 if max_bins <= 256 else np.uint16)
    m_xla = _margins(spec, binned, "xla")
    m_pal = _margins(spec, binned, "pallas")
    np.testing.assert_array_equal(m_xla, m_pal)


def test_logistic_finalize_parity_through_scorer(spark, infer_conf):
    """DeviceScorer.score_block on a boosted BINARY model: the sigmoid
    finalize sits on top of bit-identical margins, so kernel-path
    probabilities equal the XLA path's exactly. The scorer's resolved
    spec is surfaced by kernel_spec()."""
    from sml_tpu.ml.inference import DeviceScorer
    X, y = _toy()
    yb = (y > np.median(y)).astype(np.float32)
    spec = _fit_kind("xgb", X, yb, 32)
    spec_l = spec  # squared-boosted; refit logistic for the sigmoid path
    from sml_tpu.ml._tree_models import _fit_ensemble
    spec_l = _fit_ensemble(X, yb, categorical={}, max_depth=4, max_bins=32,
                           min_instances=1, min_info_gain=0.0, n_trees=5,
                           feature_k=None, bootstrap=False, subsample=1.0,
                           seed=7, loss="logistic", boosting=True)
    assert spec_l.mode == "binary"
    scorer = DeviceScorer(types.SimpleNamespace(_spec=spec_l))
    GLOBAL_CONF.set("sml.dispatch.mode", "device")  # pin the kernel route
    GLOBAL_CONF.set("sml.infer.kernel", "xla")
    p_xla = scorer.score_block(X)
    GLOBAL_CONF.set("sml.infer.kernel", "pallas")
    p_pal = scorer.score_block(X)
    np.testing.assert_array_equal(p_xla, p_pal)
    assert np.all((p_pal >= 0.0) & (p_pal <= 1.0))
    ks = scorer.kernel_spec()
    assert ks is not None and ks["kernel"] == "pallas"


def test_forest_eval_parity_bitwise(spark, infer_conf):
    """The fused predict+metric eval program under the kernel path:
    bit-identical margins feed the same psums, so all five sufficient
    statistics are exactly equal."""
    from sml_tpu.ml import tree_impl
    from sml_tpu.ml._staging import run_data_parallel
    from sml_tpu.ml.inference import forest_eval_fn
    X, y = _toy()
    spec = _fit_kind("rf", X, y, 32)
    binned = tree_impl.bin_with(np.asarray(X, np.float64), spec.binning)
    sf, sb, lv, w = spec.stacked()
    l32 = np.nan_to_num(y).astype(np.float32)
    f32 = np.isfinite(y).astype(np.float32)
    rep = (np.asarray(sf), np.asarray(sb),
           np.asarray(lv, dtype=np.float32),
           np.asarray(w, dtype=np.float32), np.float32(spec.base))
    stats_x = run_data_parallel(forest_eval_fn(spec.depth, "identity"),
                                binned, l32, f32, replicated=rep)
    stats_p = run_data_parallel(
        forest_eval_fn(spec.depth, "identity", "pallas", 2048),
        binned, l32, f32, replicated=rep)
    for a, b in zip(stats_x, stats_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------- counters & health
def test_kernel_counters_report_and_health(spark, infer_conf):
    """The kernel path traces pallas launches (interpret on CPU), the
    module report carries the resolved spec, and engine_health()
    surfaces it as the infer_kernel block."""
    import sml_tpu.obs as obs
    from sml_tpu.ml import inference, tree_impl
    X, y = _toy(n=3000)
    spec = _fit_kind("rf", X, y, 32)
    binned = tree_impl.bin_with(np.asarray(X, np.float64), spec.binning)
    prev_obs = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    obs.reset()
    try:
        _margins(spec, binned, "xla")   # guarantee a spec TRANSITION so
        _margins(spec, binned, "pallas")  # the change event fires below
        c = obs.RECORDER.counters()
        assert c.get("kernel.pallas_launch", 0.0) > 0
        assert c.get("kernel.interpret", 0.0) > 0  # CPU = interpret mode
        assert c.get("infer.kernel.pallas", 0.0) >= 1
        rep = inference.kernel_report()
        assert rep["kernel"] == "pallas" and rep["block_rows"] > 0
        health = obs.engine_health()
        assert health["infer_kernel"]["kernel"] == "pallas"
        assert health["infer_kernel"]["fallbacks"] == rep["fallbacks"]
        events = [e for e in obs.RECORDER.events()
                  if e.name == "infer.kernel.spec"]
        assert events and events[-1].args["kernel"] == "pallas"
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", prev_obs)


def test_auto_never_selects_pallas_on_cpu(spark, infer_conf):
    """'auto' = pallas on real TPU only; CPU emulation is an explicit
    opt-in, and landing on xla via auto is NOT a fallback."""
    from sml_tpu.ml import inference
    GLOBAL_CONF.set("sml.infer.kernel", "auto")
    f0 = inference._KERNEL_STATE["fallbacks"]
    k, br, tuned = inference.resolve_infer_kernel(
        n_trees=5, depth=4, n_nodes=31, n_feat=8, n_bins=32, n_rows=4096)
    assert (k, br, tuned) == ("xla", 0, False)
    assert inference._KERNEL_STATE["fallbacks"] == f0
    GLOBAL_CONF.set("sml.infer.kernel", "bogus")
    with pytest.raises(ValueError, match="sml.infer.kernel"):
        inference.resolve_infer_kernel(
            n_trees=5, depth=4, n_nodes=31, n_feat=8, n_bins=32,
            n_rows=4096)


def test_fallback_when_kernel_unavailable(spark, infer_conf, monkeypatch):
    """Requested pallas with a dead toolchain: the resolver lands on xla
    and counts infer.kernel.fallback — scoring never crashes."""
    from sml_tpu.ml import inference, tree_impl
    from sml_tpu.native import hist_kernel
    monkeypatch.setitem(hist_kernel._avail, "ok", False)
    GLOBAL_CONF.set("sml.infer.kernel", "pallas")
    f0 = inference._KERNEL_STATE["fallbacks"]
    k, br, _ = inference.resolve_infer_kernel(
        n_trees=5, depth=4, n_nodes=31, n_feat=8, n_bins=32, n_rows=4096)
    assert (k, br) == ("xla", 0)
    assert inference._KERNEL_STATE["fallbacks"] == f0 + 1
    X, y = _toy(n=2000)
    spec = _fit_kind("dt", X, y, 32)
    binned = tree_impl.bin_with(np.asarray(X, np.float64), spec.binning)
    m = _margins(spec, binned, "pallas")  # scores via the xla fallback
    GLOBAL_CONF.set("sml.infer.kernel", "xla")
    np.testing.assert_array_equal(m, _margins(spec, binned, "xla"))


def test_vmem_guard_demotes_oversized_specs_on_tpu(spark, infer_conf):
    """On (simulated) real TPU the resolver clamps block_rows to the
    traversal VMEM budget, and a spec whose resident node tables alone
    bust it demotes to xla with fallback + demotion counts; CPU
    interpret mode never clamps or demotes."""
    from sml_tpu.ml import inference, tree_impl
    from sml_tpu.parallel import mesh as meshlib
    GLOBAL_CONF.set("sml.infer.kernel", "pallas")
    GLOBAL_CONF.set("sml.infer.kernelBlockRows", 10 ** 6)
    k, br, _ = inference.resolve_infer_kernel(
        n_trees=8, depth=5, n_nodes=63, n_feat=10, n_bins=32,
        n_rows=4096)
    assert (k, br) == ("pallas", 10 ** 6)  # CPU: conf taken verbatim
    mesh = meshlib.get_mesh()
    tree_impl._platform_memo[id(mesh)] = (mesh, "tpu")  # simulate TPU
    try:
        k, br, _ = inference.resolve_infer_kernel(
            n_trees=8, depth=5, n_nodes=63, n_feat=10, n_bins=32,
            n_rows=4096)
        assert k == "pallas" and 8 <= br < 10 ** 6  # clamped to budget
        from sml_tpu.native import traverse_kernel as _tk
        assert br == _tk.max_block_rows(8, 63, 10)  # ONE arithmetic
        f0 = inference._KERNEL_STATE["fallbacks"]
        d0 = inference._KERNEL_STATE["demotions"]
        k, br, _ = inference.resolve_infer_kernel(
            n_trees=2000, depth=10, n_nodes=2047, n_feat=10, n_bins=32,
            n_rows=4096)  # 2000×2047 node tables >> the VMEM budget
        assert (k, br) == ("xla", 0)
        assert inference._KERNEL_STATE["fallbacks"] == f0 + 1
        assert inference._KERNEL_STATE["demotions"] == d0 + 1
    finally:
        tree_impl._platform_memo.clear()


# ------------------------------------------------- autotuned spec roundtrip
def test_tuned_spec_roundtrip_through_prewarm_manifest(spark, infer_conf,
                                                       tmp_path):
    """record_tuned → manifest entry → resolver picks the tuned spec
    (overriding conf) without a sweep; re-tuning REPLACES the entry; a
    different batch width misses; the infer_kernel rebuilder replays the
    recorded program into the live caches."""
    from sml_tpu.ml import inference
    from sml_tpu.parallel import mesh as meshlib, prewarm
    prev_dir = GLOBAL_CONF.get("sml.compile.cacheDir")
    GLOBAL_CONF.set("sml.compile.cacheDir", str(tmp_path))
    try:
        GLOBAL_CONF.set("sml.infer.autotune", True)
        GLOBAL_CONF.set("sml.infer.kernel", "xla")  # tuned spec must win
        key = inference.infer_spec_key(5, 4, 10, 32, 4096)
        assert prewarm.tuned_spec("infer_kernel", key) is None
        prewarm.record_tuned("infer_kernel", key,
                             {"kernel": "pallas", "block_rows": 512})
        assert prewarm.tuned_spec("infer_kernel", key) \
            == {"kernel": "pallas", "block_rows": 512}
        k, br, tuned = inference.resolve_infer_kernel(
            n_trees=5, depth=4, n_nodes=31, n_feat=10, n_bins=32,
            n_rows=4096)
        assert (k, br, tuned) == ("pallas", 512, True)
        assert inference.kernel_report()["tuned"] is True
        # re-tune REPLACES (stable manifest key), never accumulates
        prewarm.record_tuned("infer_kernel", key,
                             {"kernel": "xla", "block_rows": 0})
        assert prewarm.tuned_spec("infer_kernel", key) \
            == {"kernel": "xla", "block_rows": 0}
        mpath = os.path.join(str(tmp_path), "prewarm_manifest.json")
        with open(mpath) as f:
            entries = json.load(f)["entries"]
        tuned = [e for e in entries.values()
                 if e["kind"] == "infer_kernel"]
        assert len(tuned) == 1
        # a different batch width is a different key: conf path resolves
        k2, br2, tuned2 = inference.resolve_infer_kernel(
            n_trees=5, depth=4, n_nodes=31, n_feat=10, n_bins=32,
            n_rows=262144)
        assert (k2, br2, tuned2) == ("xla", 0, False)
        assert inference.kernel_report()["tuned"] is False
        # autotune off: the manifest is ignored entirely
        prewarm.record_tuned("infer_kernel", key,
                             {"kernel": "pallas", "block_rows": 512})
        GLOBAL_CONF.set("sml.infer.autotune", False)
        k3, _, _ = inference.resolve_infer_kernel(
            n_trees=5, depth=4, n_nodes=31, n_feat=10, n_bins=32,
            n_rows=4096)
        assert k3 == "xla"
        # the prewarm rebuilder replays the tuned program into the SAME
        # cache the live score path hits (replica spin-up's warm start)
        inference._replay_infer_kernel(
            {"key": key, "spec": {"kernel": "pallas", "block_rows": 512}})
        mesh = meshlib.get_mesh()
        assert (4, id(mesh), "pallas", 512) in inference._forest_programs
    finally:
        GLOBAL_CONF.set("sml.compile.cacheDir", prev_dir or "")


# --------------------------------------------------------- regress rules
def test_regress_kernel_infer_rules(spark):
    """obs/regress.py: a vanished kernel_infer sidecar block, fallback
    growth, or a lost beats-default/replay proof is a regression;
    driver-shaped records are exempt from the coverage rule."""
    from sml_tpu.obs import regress
    block = {"fallbacks": 0.0, "tuned_beats_default": True,
             "replay_ok": True, "legs": []}
    base = regress.normalize({"legs": {}, "kernel_infer": dict(block)})
    ok = regress.compare(base, regress.normalize(
        {"legs": {}, "kernel_infer": dict(block)}))
    assert ok["ok"]
    gone = regress.compare(base, regress.normalize({"legs": {}}))
    assert not gone["ok"]
    assert any(f["kind"] == "missing-kernel-infer-block"
               for f in gone["regressions"])
    # driver records can never carry the block: exempt
    rec = regress.compare(base, regress.normalize(
        {"parsed": {}, "tail": ""}))
    assert not any(f["kind"] == "missing-kernel-infer-block"
                   for f in rec["regressions"])
    fell = regress.compare(base, regress.normalize(
        {"legs": {}, "kernel_infer": dict(block, fallbacks=2.0)}))
    assert any(f["kind"] == "infer-kernel-fallback"
               for f in fell["regressions"])
    lost = regress.compare(base, regress.normalize(
        {"legs": {},
         "kernel_infer": dict(block, tuned_beats_default=False)}))
    assert any(f["key"] == "tuned_beats_default"
               for f in lost["regressions"])
    lost2 = regress.compare(base, regress.normalize(
        {"legs": {}, "kernel_infer": dict(block, replay_ok=False)}))
    assert any(f["key"] == "replay_ok" for f in lost2["regressions"])
    # interpret-mode runs: every pallas block_rows candidate is the
    # identical single-block program, so beats-default is timer noise —
    # NOT judged as a proof (replay_ok still is)
    ib = dict(block, interpret=True)
    base_i = regress.normalize({"legs": {}, "kernel_infer": dict(ib)})
    lost_i = regress.compare(base_i, regress.normalize(
        {"legs": {},
         "kernel_infer": dict(ib, tuned_beats_default=False)}))
    assert not any(f["key"] == "tuned_beats_default"
                   for f in lost_i["regressions"])
    lost_i2 = regress.compare(base_i, regress.normalize(
        {"legs": {}, "kernel_infer": dict(ib, replay_ok=False)}))
    assert any(f["key"] == "replay_ok" for f in lost_i2["regressions"])


def test_block_plan_never_reads_conf_at_trace_time():
    """PR-18 regression (the untracked-compile-input lint fix): the
    traversal kernel's block plan is a pure function of its arguments.
    The pre-fix fallback read `sml.infer.kernelBlockRows` from live
    conf at TRACE time, silently diverging from the cache-keyed value
    `inference.resolve_infer_kernel` resolved host-side."""
    import inspect

    from sml_tpu.conf import GLOBAL_CONF
    from sml_tpu.native import traverse_kernel as tk

    src = inspect.getsource(tk._block_plan)
    assert "GLOBAL_CONF" not in src, \
        "trace-time conf read reintroduced into _block_plan"
    # None/0 now mean "no blocking": one full block, conf untouched
    assert tk._block_plan(4096, False, None) == (1, 4096)
    assert tk._block_plan(4096, False, 0) == (1, 4096)
    assert tk._block_plan(4096, True, 256) == (1, 4096)
    nblk, blk = tk._block_plan(4096, False, 256)
    assert nblk * blk == 4096 and blk <= 256
    prev = GLOBAL_CONF.get("sml.infer.kernelBlockRows")
    try:
        GLOBAL_CONF.set("sml.infer.kernelBlockRows", 7)
        assert tk._block_plan(4096, False, None) == (1, 4096)
        assert tk._block_plan(4096, False, 256) == (nblk, blk)
    finally:
        GLOBAL_CONF.set("sml.infer.kernelBlockRows", prev)
