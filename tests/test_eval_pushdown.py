"""Evaluator pushdown (`_fused_eval` hooks): RegressionEvaluator on a LAZY
model-transform frame computes its metric without materializing the frame,
and the value must match the ordinary materialize path exactly enough to be
indistinguishable (same predictions, f32-sum-order differences only).

Covers the two hook producers: `_TreeRegressionModel._transform`
(fused traverse+stats device program) and the fused `PipelineModel`
transform (`_ScorerEvalHook`: featurize + routed predict + host stats)."""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.ml import Pipeline
from sml_tpu.ml.evaluation import RegressionEvaluator
from sml_tpu.ml.feature import (Imputer, OneHotEncoder, StringIndexer,
                                VectorAssembler)
from sml_tpu.ml.regression import LinearRegression, RandomForestRegressor


def _frame(spark, n=4000, seed=7, with_nan_label=True):
    rng = np.random.default_rng(seed)
    pdf = pd.DataFrame({
        "cat": rng.choice(["a", "b", "c"], n),
        "x1": rng.normal(2.0, 1.0, n),
        "x2": rng.normal(-1.0, 2.0, n),
        "label": rng.normal(100.0, 20.0, n),
    })
    if with_nan_label:
        pdf.loc[::97, "label"] = np.nan  # finite-filter parity
    return spark.createDataFrame(pdf)


def test_tree_eval_pushdown_matches_materialized(spark):
    df = _frame(spark)
    feats = Pipeline(stages=[
        StringIndexer(inputCols=["cat"], outputCols=["cat_idx"]),
        VectorAssembler(inputCols=["cat_idx", "x1", "x2"],
                        outputCol="features"),
    ]).fit(df).transform(df)
    feats.cache()
    model = RandomForestRegressor(labelCol="label", numTrees=5, maxDepth=4,
                                  seed=42).fit(feats)
    ev = RegressionEvaluator(labelCol="label")

    lazy = model.transform(feats)
    assert getattr(lazy, "_fused_eval", None) is not None
    assert lazy._parts is None
    rmse_hook = ev.evaluate(lazy)
    # hook path must not have materialized the frame
    assert lazy._parts is None

    materialized = model.transform(feats)
    materialized.toPandas()
    rmse_plain = ev.evaluate(materialized)
    assert rmse_hook == pytest.approx(rmse_plain, rel=1e-5)
    # r2 exercises the sl/sl2 statistics; 1 - mse/var amplifies the
    # f32-sum-order difference by ~1/(1-r2), so gate absolutely
    ev2 = RegressionEvaluator(labelCol="label", metricName="r2")
    assert ev2.evaluate(model.transform(feats)) == \
        pytest.approx(ev2.evaluate(materialized), abs=5e-4)


def test_pipeline_eval_pushdown_matches_materialized(spark):
    df = _frame(spark)
    model = Pipeline(stages=[
        Imputer(strategy="median", inputCols=["x1", "x2"],
                outputCols=["x1_i", "x2_i"]),
        StringIndexer(inputCols=["cat"], outputCols=["cat_idx"],
                      handleInvalid="skip"),
        OneHotEncoder(inputCols=["cat_idx"], outputCols=["cat_ohe"]),
        VectorAssembler(inputCols=["cat_ohe", "x1_i", "x2_i"],
                        outputCol="features"),
        LinearRegression(labelCol="label"),
    ]).fit(df)
    ev = RegressionEvaluator(labelCol="label")

    lazy = model.transform(df)
    assert getattr(lazy, "_fused_eval", None) is not None
    assert lazy._parts is None
    rmse_hook = ev.evaluate(lazy)
    assert lazy._parts is None  # never materialized

    materialized = model.transform(df)
    materialized.toPandas()
    rmse_plain = ev.evaluate(materialized)
    assert rmse_hook == pytest.approx(rmse_plain, rel=1e-5)


def test_pushdown_declines_when_label_is_produced(spark):
    """A prep stage overwriting labelCol means raw labels are stale: the
    hook must decline and the materialize path must serve the metric."""
    df = _frame(spark, with_nan_label=False)
    model = Pipeline(stages=[
        Imputer(strategy="median", inputCols=["label"],
                outputCols=["label"]),  # writes labelCol in place
        VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
        LinearRegression(labelCol="label"),
    ]).fit(df)
    lazy = model.transform(df)
    hook = getattr(lazy, "_fused_eval", None)
    if hook is not None:
        assert hook.reg_stats("prediction", "label") is None
    ev = RegressionEvaluator(labelCol="label")
    assert np.isfinite(ev.evaluate(model.transform(df)))


def test_pushdown_ignored_for_mismatched_prediction_col(spark):
    df = _frame(spark)
    feats = VectorAssembler(inputCols=["x1", "x2"], outputCol="features") \
        .transform(df)
    model = RandomForestRegressor(labelCol="label", numTrees=3, maxDepth=3,
                                  seed=1, predictionCol="my_pred").fit(feats)
    lazy = model.transform(feats)
    # evaluator asks for the default "prediction": hook declines, normal
    # path raises/handles as it always did — here the column exists under
    # the model's name, so evaluating with the right name still works
    ev = RegressionEvaluator(labelCol="label", predictionCol="my_pred")
    assert np.isfinite(ev.evaluate(lazy))
    assert lazy._fused_eval.reg_stats("prediction", "label") is None


def test_link_pushdown_matches_materialized(spark):
    """ML 11's shape: fit on log(label), evaluate exp(prediction) on the
    raw scale. The withColumn(exp(pred)) frame keeps a LINKED fused-eval
    hook whose device program applies exp inside; the metric must equal
    the materialized path exactly."""
    import numpy as np
    import pandas as pd
    from sml_tpu.frame import functions as F
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import GBTRegressor

    rng = np.random.default_rng(5)
    n = 6000
    pdf = pd.DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})
    pdf["price"] = np.exp(0.5 * pdf.x1 - 0.2 * pdf.x2
                          + rng.normal(0, 0.1, n) + 3.0)
    df = spark.createDataFrame(pdf)
    train, test = df.randomSplit([0.8, 0.2], seed=42)
    log_train = train.withColumn("label", F.log(F.col("price")))
    log_test = test.withColumn("label", F.log(F.col("price")))
    va = VectorAssembler(inputCols=["x1", "x2"], outputCol="features")
    m = Pipeline(stages=[va, GBTRegressor(labelCol="label", maxDepth=3,
                                          maxIter=8)]).fit(log_train)
    pred = m.transform(log_test).withColumn(
        "prediction", F.exp(F.col("prediction")))
    # linked hook is attached and tagged
    hook = getattr(pred, "_fused_eval", None)
    assert hook is not None and hook._link == "exp"
    ev = RegressionEvaluator(labelCol="price", metricName="rmse")
    rmse_hook = ev.evaluate(pred)
    assert pred._parts is None  # the hook served; no materialization
    # materialized ground truth
    pp = m.transform(log_test).toPandas()
    truth = float(np.sqrt(np.mean(
        (np.exp(pp["prediction"]) - pp["price"]) ** 2)))
    assert abs(rmse_hook - truth) < 1e-6 * max(truth, 1.0)

    # a link over a NON-prediction column must drop the hook, and a
    # second link over an already-linked hook must too
    other = m.transform(log_test).withColumn("price",
                                             F.exp(F.col("price")))
    assert getattr(other, "_fused_eval", None) is None
    double = pred.withColumn("prediction", F.exp(F.col("prediction")))
    assert getattr(double, "_fused_eval", None) is None


def test_link_pushdown_on_bare_tree_transform(spark):
    """The link propagation also covers the CV/tuning shape: a bare tree
    model's transform over a featurized frame carries _TreeEvalHook, and
    withColumn(exp(pred)) keeps it linked."""
    from sml_tpu.frame import functions as F

    rng = np.random.default_rng(9)
    n = 5000
    pdf = pd.DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})
    pdf["label"] = 0.4 * pdf.x1 - 0.3 * pdf.x2 + rng.normal(0, 0.1, n) + 2.0
    pdf["price"] = np.exp(pdf["label"])
    df = spark.createDataFrame(pdf)
    feat = Pipeline(stages=[VectorAssembler(
        inputCols=["x1", "x2"], outputCol="features")]).fit(df).transform(df)
    feat.cache()
    m = RandomForestRegressor(labelCol="label", maxDepth=4, numTrees=6,
                              seed=3).fit(feat)
    pred = m.transform(feat).withColumn("prediction",
                                       F.exp(F.col("prediction")))
    hook = getattr(pred, "_fused_eval", None)
    assert hook is not None and hook._link == "exp"
    rmse = RegressionEvaluator(labelCol="price",
                               metricName="rmse").evaluate(pred)
    # the HOOK must have served the metric: the lazy frame stays
    # unmaterialized (otherwise this only re-tests the fallback path)
    assert pred._parts is None
    pp = m.transform(feat).toPandas()
    truth = float(np.sqrt(np.mean(
        (np.exp(pp["prediction"]) - pp["price"]) ** 2)))
    assert abs(rmse - truth) < 1e-6 * max(truth, 1.0)
