"""Mesh-sharded batch inference (SURVEY §2.2 P8, `ML 12`).

r1 had no device path for the pandas-UDF surface — model-backed UDF bodies
looped on host. DeviceScorer + the sharded predict programs are that path.
"""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.ml import DeviceScorer, Pipeline
from sml_tpu.ml.feature import VectorAssembler
from sml_tpu.ml.regression import (LinearRegression, RandomForestRegressor)
from sml_tpu.ml.classification import LogisticRegression


@pytest.fixture()
def fitted_lr(spark, airbnb_pdf):
    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates", "bathrooms"],
                         outputCol="features")
    lr = LinearRegression(featuresCol="features", labelCol="price")
    pipe = Pipeline(stages=[va, lr]).fit(df)
    return pipe, df


def test_device_scorer_matches_transform_linear(fitted_lr):
    pipe, df = fitted_lr
    expected = pipe.transform(df).toPandas()["prediction"].to_numpy()
    scorer = DeviceScorer(pipe)
    got = scorer(df.toPandas())
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_device_scorer_raw_block(fitted_lr):
    pipe, df = fitted_lr
    lr_model = pipe.stages[-1]
    scorer = DeviceScorer(lr_model)
    X = np.random.default_rng(0).normal(size=(100, 3)).astype(np.float32)
    w = lr_model.coefficients.toArray()
    b = lr_model.intercept
    np.testing.assert_allclose(scorer.score_block(X), X @ w + b, rtol=1e-4)


def test_device_scorer_forest(spark, airbnb_pdf):
    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates", "bathrooms"],
                         outputCol="features")
    rf = RandomForestRegressor(featuresCol="features", labelCol="price",
                               numTrees=5, maxDepth=4, seed=42)
    pipe = Pipeline(stages=[va, rf]).fit(df)
    expected = pipe.transform(df).toPandas()["prediction"].to_numpy()
    got = DeviceScorer(pipe)(df.toPandas())
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_device_scorer_logistic(spark, airbnb_pdf):
    pdf = airbnb_pdf.copy()
    pdf["expensive"] = (pdf["price"] > pdf["price"].median()).astype(float)
    df = spark.createDataFrame(pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                         outputCol="features")
    logr = LogisticRegression(featuresCol="features", labelCol="expensive")
    pipe = Pipeline(stages=[va, logr]).fit(df)
    probs = pipe.transform(df).toPandas()["probability"]
    expected = probs.array.block[:, 1]
    got = DeviceScorer(pipe)(df.toPandas())
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-6)


def test_score_batches_pipelined(fitted_lr):
    pipe, df = fitted_lr
    scorer = DeviceScorer(pipe)
    pdf = df.toPandas()
    batches = [pdf.iloc[i:i + 500] for i in range(0, len(pdf), 500)]
    outs = list(scorer.score_batches(batches))
    assert len(outs) == len(batches)
    whole = scorer(pdf)
    np.testing.assert_allclose(np.concatenate(outs), whole, rtol=1e-5)


def test_prefetch_depth_configurable_and_overlap(fitted_lr):
    """`sml.infer.prefetchBatches` replaces the hard-coded lookahead, and
    the recorder's infer.* events prove the pipelining claim: batch i+1's
    dispatch (prep + staging) lands BEFORE batch i's drain — staging of
    the next batch overlaps compute/readback of the current one."""
    import sml_tpu.obs as obs
    from sml_tpu.conf import GLOBAL_CONF
    pipe, _ = fitted_lr
    # the tail model alone: no featurizer -> the pipelined dispatch loop
    # (the factorized-linear branch is pure host work with no events)
    scorer = DeviceScorer(pipe.stages[-1])
    X = np.random.default_rng(2).normal(size=(4000, 3)).astype(np.float32)
    batches = [X[i:i + 500] for i in range(0, 4000, 500)]
    old = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    GLOBAL_CONF.set("sml.infer.prefetchBatches", 3)
    obs.reset()
    try:
        outs = list(scorer.score_batches(batches))
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", old)
        GLOBAL_CONF.unset("sml.infer.prefetchBatches")
    assert len(outs) == len(batches)
    events = [(e.name, e.args.get("batch")) for e in obs.RECORDER.events()
              if e.name.startswith("infer.")]
    first_drain = events.index(("infer.drain", 0))
    ahead = {b for name, b in events[:first_drain]
             if name == "infer.dispatch"}
    assert {0, 1, 2} <= ahead  # depth=3: three dispatches before drain 0
    np.testing.assert_allclose(np.concatenate(outs), scorer.score_block(X),
                               rtol=1e-6)


def test_factorized_fallback_on_missing_column_mid_stream(fitted_lr):
    """A batch missing a raw column mid-stream kills BOTH compiled
    layers permanently (`__call__`'s KeyError → `self._factorized =
    None`, then `_prep`'s KeyError → `self._featurizer = None`): the bad
    batch itself raises (no layer can conjure the column), but every
    later complete batch still scores correctly through the generic
    stage path — and score_batches switches from the factorized host map
    to the dispatch pipeline."""
    pipe, df = fitted_lr
    scorer = DeviceScorer(pipe)
    assert scorer._factorized is not None and scorer._featurizer is not None
    pdf = df.toPandas()
    expected = scorer(pdf)
    bad = pdf.drop(columns=["bathrooms"])
    batches = [pdf.iloc[:500], bad, pdf.iloc[500:1000]]
    it = scorer.score_batches(iter(batches))
    np.testing.assert_allclose(next(it), expected[:500], rtol=1e-5)
    with pytest.raises(KeyError, match="bathrooms"):
        for _ in it:
            pass
    # the fallback is PERMANENT, not per-batch retried
    assert scorer._factorized is None and scorer._featurizer is None
    # a fresh stream of complete batches scores through the generic
    # stage path (prefetch_pipeline now — factorized is gone) and
    # matches the factorized results
    outs = list(scorer.score_batches([pdf.iloc[i:i + 500]
                                      for i in range(0, len(pdf), 500)]))
    np.testing.assert_allclose(np.concatenate(outs), expected, rtol=1e-5)


def test_prep_featurizer_keyerror_falls_back_to_stages(spark, airbnb_pdf):
    """`_prep`'s compiled-featurizer KeyError fallback, isolated from the
    factorized-linear layer: a forest pipeline has a featurizer but no
    factorized scorer, so the missing-column batch exercises exactly the
    `self._featurizer = None` branch; later batches ride the generic
    stage path with identical predictions."""
    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates",
                                    "bathrooms"], outputCol="features")
    rf = RandomForestRegressor(featuresCol="features", labelCol="price",
                               numTrees=4, maxDepth=3, seed=1)
    pipe = Pipeline(stages=[va, rf]).fit(df)
    scorer = DeviceScorer(pipe)
    assert scorer._factorized is None and scorer._featurizer is not None
    pdf = df.toPandas()
    expected = scorer(pdf)
    with pytest.raises(KeyError, match="accommodates"):
        scorer(pdf.drop(columns=["accommodates"]))
    assert scorer._featurizer is None  # permanent generic-stage fallback
    got = scorer(pdf)
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_sharded_predict_large_batch_matches_small(fitted_lr):
    """The >=4096-row sharded path and the single-device path must agree."""
    pipe, _ = fitted_lr
    lr_model = pipe.stages[-1]
    rng = np.random.default_rng(1)
    X = rng.normal(size=(5000, 3)).astype(np.float32)
    from sml_tpu.ml.linear_impl import predict_linear
    big = predict_linear(X, lr_model.coefficients.toArray(), lr_model.intercept)
    small = np.concatenate([
        predict_linear(X[i:i + 1000], lr_model.coefficients.toArray(),
                       lr_model.intercept) for i in range(0, 5000, 1000)])
    np.testing.assert_allclose(big, small, rtol=1e-5)


def test_pyfunc_predict_uses_device_path(spark, airbnb_pdf, tmp_path):
    import sml_tpu.tracking as mlflow
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                         outputCol="features")
    lr = LinearRegression(featuresCol="features", labelCol="price")
    pipe = Pipeline(stages=[va, lr]).fit(df)
    with mlflow.start_run() as run:
        mlflow.spark.log_model(pipe, "model")
    loaded = mlflow.pyfunc.load_model(f"runs:/{run.info.run_id}/model")
    preds = loaded.predict(airbnb_pdf)
    expected = pipe.transform(df).toPandas()["prediction"].to_numpy()
    np.testing.assert_allclose(np.asarray(preds), expected, rtol=1e-5)
    assert loaded._scorer is not None  # device path engaged, not fallback
