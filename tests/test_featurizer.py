"""CompiledFeaturizer parity: the fused columnar pass must reproduce the
generic per-stage transform chain bit-for-bit (ml/featurizer.py)."""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.frame.session import get_session
from sml_tpu.ml import DeviceScorer, Pipeline
from sml_tpu.ml.feature import (Imputer, OneHotEncoder, StringIndexer,
                                VectorAssembler)
from sml_tpu.ml.featurizer import CompiledFeaturizer
from sml_tpu.ml.regression import LinearRegression
from sml_tpu.ml._staging import extract_features


def _data(n=400, seed=0, nan_rate=0.1):
    rng = np.random.default_rng(seed)
    pdf = pd.DataFrame({
        "cat": rng.choice(["a", "b", "c", "d"], size=n),
        "x1": rng.normal(size=n),
        "x2": rng.normal(size=n),
        "label": rng.normal(size=n),
    })
    pdf.loc[rng.random(n) < nan_rate, "x1"] = np.nan
    return pdf


def _pipeline(handle_invalid="keep"):
    return Pipeline(stages=[
        Imputer(strategy="median", inputCols=["x1", "x2"],
                outputCols=["x1_i", "x2_i"]),
        StringIndexer(inputCols=["cat"], outputCols=["cat_idx"],
                      handleInvalid=handle_invalid),
        OneHotEncoder(inputCols=["cat_idx"], outputCols=["cat_ohe"]),
        VectorAssembler(inputCols=["cat_ohe", "x1_i", "x2_i"],
                        outputCol="features"),
        LinearRegression(labelCol="label"),
    ])


def _generic_features(model, pdf):
    df = get_session().createDataFrame(pdf)
    for s in model.stages[:-1]:
        df = s.transform(df)
    return extract_features(df.toPandas(), "features")


@pytest.mark.parametrize("invalid", ["keep", "skip", "error"])
def test_featurizer_matches_generic_chain(invalid):
    pdf = _data()
    model = _pipeline(invalid).fit(get_session().createDataFrame(pdf))
    feat = CompiledFeaturizer.from_stages(model.stages[:-1], model.stages[-2])
    assert feat is not None
    batch = _data(seed=1)
    np.testing.assert_allclose(feat(batch), _generic_features(model, batch),
                               rtol=1e-6)


def test_featurizer_skip_drops_unseen_rows():
    pdf = _data()
    model = _pipeline("skip").fit(get_session().createDataFrame(pdf))
    feat = CompiledFeaturizer.from_stages(model.stages[:-1], model.stages[-2])
    batch = _data(seed=2)
    batch.loc[:4, "cat"] = "UNSEEN"
    out = feat(batch)
    ref = _generic_features(model, batch)
    assert out.shape == ref.shape == (len(batch) - 5, ref.shape[1])
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_featurizer_keep_maps_unseen_to_extra_index():
    pdf = _data()
    model = _pipeline("keep").fit(get_session().createDataFrame(pdf))
    feat = CompiledFeaturizer.from_stages(model.stages[:-1], model.stages[-2])
    batch = _data(seed=3)
    batch.loc[:4, "cat"] = "UNSEEN"
    np.testing.assert_allclose(feat(batch), _generic_features(model, batch),
                               rtol=1e-6)


def test_featurizer_error_raises_on_unseen():
    pdf = _data()
    model = _pipeline("error").fit(get_session().createDataFrame(pdf))
    feat = CompiledFeaturizer.from_stages(model.stages[:-1], model.stages[-2])
    batch = _data(seed=4)
    batch.loc[0, "cat"] = "UNSEEN"
    with pytest.raises(ValueError, match="Unseen label"):
        feat(batch)


def test_scorer_uses_featurizer_and_matches_transform():
    pdf = _data()
    df = get_session().createDataFrame(pdf)
    model = _pipeline("keep").fit(df)
    scorer = DeviceScorer(model)
    assert scorer._featurizer is not None
    batch = _data(seed=5)
    preds = scorer(batch)
    ref = model.transform(get_session().createDataFrame(batch)) \
        .toPandas()["prediction"].to_numpy()
    # atol floor: the factorized scorer reassociates the dot (embedding
    # sums instead of a one-hot matmul) — near-zero predictions differ at
    # the f32-quantization level
    np.testing.assert_allclose(preds, ref, rtol=1e-5, atol=1e-7)


def test_factorized_scorer_matches_block_path():
    """The embedding-sum linear scorer must reproduce the one-hot block
    path exactly: same predictions, same handleInvalid='skip' row drops,
    NaN propagation for unseen-under-'keep' rows."""
    pdf = _data()
    df = get_session().createDataFrame(pdf)
    for invalid in ("keep", "skip"):
        model = _pipeline(invalid).fit(df)
        scorer = DeviceScorer(model)
        assert scorer._factorized is not None
        batch = _data(seed=8)
        batch.loc[batch.index[:5], "cat"] = "ZZ_UNSEEN"
        fast = scorer(batch)
        scorer2 = DeviceScorer(model)
        scorer2._factorized = None  # force the block path
        ref = scorer2(batch)
        assert fast.shape == ref.shape
        np.testing.assert_allclose(fast, ref, rtol=1e-5, atol=1e-7,
                                   equal_nan=True)


def test_featurizer_rejects_unknown_stage():
    from sml_tpu.ml.feature import StandardScaler
    pdf = _data(nan_rate=0)
    df = get_session().createDataFrame(pdf)
    model = Pipeline(stages=[
        VectorAssembler(inputCols=["x1", "x2"], outputCol="raw"),
        StandardScaler(inputCol="raw", outputCol="features"),
        LinearRegression(labelCol="label"),
    ]).fit(df)
    scorer = DeviceScorer(model)
    assert scorer._featurizer is None  # generic path still works
    preds = scorer(_data(seed=6, nan_rate=0))
    assert preds.shape == (400,)


def test_fused_pipeline_fit_matches_generic_path(monkeypatch):
    """The fused whole-pipeline fit (try_fast_fit) must produce EXACTLY the
    model the generic per-stage path produces — same coefficients, same
    predictions — for the standard course chain including NaN imputes and
    'skip' row drops."""
    from sml_tpu.ml.evaluation import RegressionEvaluator

    pdf = _data(n=3000, seed=11, nan_rate=0.15)
    df = get_session().createDataFrame(pdf)

    import sml_tpu.ml.featurizer as fz
    fast_results = []
    orig_fast_fit = fz.try_fast_fit

    def spying(*a, **k):
        out = orig_fast_fit(*a, **k)
        fast_results.append(out)
        return out

    monkeypatch.setattr(fz, "try_fast_fit", spying)
    m_fast = _pipeline("skip").fit(df)
    # the fused path must have actually run — otherwise this test compares
    # the generic path against itself and guards nothing
    assert fast_results and fast_results[-1] is not None
    monkeypatch.setattr(fz, "try_fast_fit", lambda *a, **k: None)
    monkeypatch.setattr(fz.CompiledFeaturizer, "from_stages",
                        classmethod(lambda cls, *a, **k: None))
    m_generic = _pipeline("skip").fit(get_session().createDataFrame(pdf))

    lr_fast, lr_generic = m_fast.stages[-1], m_generic.stages[-1]
    np.testing.assert_allclose(lr_fast.coefficients.toArray(),
                               lr_generic.coefficients.toArray(), rtol=1e-6)
    np.testing.assert_allclose(lr_fast.intercept, lr_generic.intercept,
                               rtol=1e-6)
    test = get_session().createDataFrame(_data(n=800, seed=12, nan_rate=0.1))
    ev = RegressionEvaluator(labelCol="label")
    r1 = ev.evaluate(m_fast.transform(test))
    r2 = ev.evaluate(m_generic.transform(test))
    assert abs(r1 - r2) < 1e-9, (r1, r2)


def test_fused_transform_matches_generic_path(monkeypatch):
    """PipelineModel.transform's fused one-pass path must reproduce the
    generic per-stage chain EXACTLY: same columns (incl. interim stage
    outputs), same values, same row drops under handleInvalid='skip',
    same ml attrs — r4's answer to VERDICT #1 (per-stage host
    materialization dominating the bench)."""
    import pandas as pd
    from sml_tpu.ml.base import PipelineModel
    from sml_tpu.ml.feature import OneHotEncoder, StringIndexer

    rng = np.random.default_rng(3)
    n = 3000
    pdf = pd.DataFrame({
        "cat": rng.choice(["a", "b", "c", "d"], n),
        "x1": rng.normal(size=n), "x2": rng.normal(size=n),
        "label": rng.normal(size=n),
    })
    pdf.loc[::11, "x1"] = np.nan
    train = get_session().createDataFrame(pdf)
    pipe = Pipeline(stages=[
        Imputer(inputCols=["x1"], outputCols=["x1_imp"], strategy="median"),
        StringIndexer(inputCols=["cat"], outputCols=["cat_idx"],
                      handleInvalid="skip"),
        OneHotEncoder(inputCols=["cat_idx"], outputCols=["cat_ohe"]),
        VectorAssembler(inputCols=["cat_ohe", "x1_imp", "x2"],
                        outputCol="features", handleInvalid="keep"),
        LinearRegression(labelCol="label"),
    ])
    model = pipe.fit(train)
    # score a batch containing an unseen label → 'skip' row drops
    test_pdf = pdf.iloc[:500].copy()
    test_pdf.loc[test_pdf.index[::7], "cat"] = "UNSEEN"
    test = get_session().createDataFrame(test_pdf)

    # the fused path must actually engage — otherwise this compares the
    # generic path with itself and guards nothing
    assert model._fast_transform(test) is not None
    fused = model.transform(test)
    fused_pdf = fused.toPandas()
    monkeypatch.setattr(PipelineModel, "_fast_transform",
                        lambda self, df: None)
    generic_pdf = model.transform(
        get_session().createDataFrame(test_pdf)).toPandas()

    assert list(fused_pdf.columns) == list(generic_pdf.columns)
    assert len(fused_pdf) == len(generic_pdf) == 500 - len(range(0, 500, 7))
    for c in ("cat_idx", "x1_imp", "prediction"):
        np.testing.assert_allclose(fused_pdf[c].to_numpy(np.float64),
                                   generic_pdf[c].to_numpy(np.float64),
                                   rtol=1e-5, atol=1e-7)
    from sml_tpu.ml._staging import extract_features
    np.testing.assert_allclose(extract_features(fused_pdf, "features"),
                               extract_features(generic_pdf, "features"),
                               rtol=1e-6)
    np.testing.assert_allclose(extract_features(fused_pdf, "cat_ohe"),
                               extract_features(generic_pdf, "cat_ohe"))
    # ml attrs parity (tree learners read these for maxBins semantics)
    gen_frame = model.transform(get_session().createDataFrame(test_pdf))
    assert fused._ml_attrs["features"]["numFeatures"] == \
        gen_frame._ml_attrs["features"]["numFeatures"]
    assert fused._ml_attrs["cat_idx"] == {"categorical": 4}


def test_fused_plan_invalidated_by_post_fit_setter():
    """A post-fit param mutation on a stage must invalidate the memoized
    fused-transform plan (r4 review): handleInvalid flipped from 'skip' to
    'keep' must stop dropping unseen-label rows."""
    import pandas as pd
    from sml_tpu.ml.feature import StringIndexer

    rng = np.random.default_rng(4)
    pdf = pd.DataFrame({"cat": rng.choice(["a", "b"], 400),
                        "x1": rng.normal(size=400),
                        "label": rng.normal(size=400)})
    model = Pipeline(stages=[
        StringIndexer(inputCols=["cat"], outputCols=["cat_idx"],
                      handleInvalid="skip"),
        VectorAssembler(inputCols=["cat_idx", "x1"], outputCol="features",
                        handleInvalid="keep"),
        LinearRegression(labelCol="label"),
    ]).fit(get_session().createDataFrame(pdf))
    test_pdf = pdf.iloc[:100].copy()
    test_pdf.loc[test_pdf.index[:10], "cat"] = "UNSEEN"
    test = get_session().createDataFrame(test_pdf)
    assert model.transform(test).count() == 90  # skip drops
    model.stages[0].setHandleInvalid("keep")
    assert model.transform(
        get_session().createDataFrame(test_pdf)).count() == 100


def test_fused_transform_pure_feature_pipeline():
    """A PipelineModel of ONLY feature stages (no final model) also takes
    the fused path — the CV leg's feat_train construction shape."""
    pdf = _data(n=2000, seed=5, nan_rate=0.1)
    df = get_session().createDataFrame(pdf)
    model = Pipeline(stages=[
        Imputer(inputCols=["x1", "x2"], outputCols=["x1i", "x2i"],
                strategy="median"),
        VectorAssembler(inputCols=["x1i", "x2i"], outputCol="features",
                        handleInvalid="keep"),
    ]).fit(df)
    out = model.transform(df)
    feats = out.toPandas()["features"]
    from sml_tpu.ml._staging import extract_features
    X = extract_features(out.toPandas(), "features")
    assert X.shape == (2000, 2) and np.isfinite(X).all()
    assert out._ml_attrs["features"]["numFeatures"] == 2


@pytest.mark.parametrize("explicit_outputs", [True, False])
def test_fused_fit_skips_when_prep_overwrites_label(explicit_outputs):
    """A prep stage that rewrites labelCol must force the generic path —
    the fused extract_xy reads labels from the RAW pandas and would
    otherwise train on pre-transform (NaN) labels (ADVICE r3). Covers both
    the explicit outputCols=['label'] form and the IN-PLACE form where
    outputCols is unset and Imputer defaults to overwriting inputCols
    (r4 review)."""
    from sml_tpu.ml.feature import Imputer

    pdf = _data(n=2000, seed=13, nan_rate=0)
    pdf.loc[::10, "label"] = np.nan
    df = get_session().createDataFrame(pdf)
    imp = (Imputer(inputCols=["label"], outputCols=["label"],
                   strategy="median") if explicit_outputs
           else Imputer(inputCols=["label"], strategy="median"))
    pipe = Pipeline(stages=[
        imp,
        VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
        LinearRegression(labelCol="label"),
    ])
    model = pipe.fit(df)
    lr = model.stages[-1]
    coef = np.asarray(lr.coefficients.toArray(), dtype=float)
    assert np.all(np.isfinite(coef)) and np.isfinite(lr.intercept)
    # Generic reference: impute the label on host first, then fit without
    # any label-touching prep stage.
    ref_pdf = pdf.copy()
    ref_pdf["label"] = ref_pdf["label"].fillna(ref_pdf["label"].median())
    ref = Pipeline(stages=[
        VectorAssembler(inputCols=["x1", "x2"], outputCol="features"),
        LinearRegression(labelCol="label"),
    ]).fit(get_session().createDataFrame(ref_pdf)).stages[-1]
    np.testing.assert_allclose(coef, ref.coefficients.toArray(), rtol=1e-5)
    np.testing.assert_allclose(lr.intercept, ref.intercept, rtol=1e-5)


def test_arrow_index_fast_path_semantics():
    """The pyarrow index_in fast path must match get_indexer semantics:
    nulls and unseen labels → NaN codes, and it must DECLINE non-string
    arrow columns — a numeric cast can collapse distinct labels like
    "1"/"1.0" onto one value (r4 review finding)."""
    import pandas as pd

    from sml_tpu.ml.featurizer import _IndexSource

    s = _IndexSource("c", np.array(["a", "b", "c"]), "keep")
    col = pd.Series(["b", None, "zz", "a", "c"], dtype="str")
    codes = s.codes(pd.DataFrame({"c": col}))
    assert codes[0] == 1.0 and codes[3] == 0.0 and codes[4] == 2.0
    assert np.isnan(codes[1]) and np.isnan(codes[2])
    # object-dtype fallback agrees
    codes_obj = s.codes(pd.DataFrame({"c": col.astype(object)}))
    np.testing.assert_array_equal(np.isnan(codes), np.isnan(codes_obj))
    np.testing.assert_array_equal(codes[~np.isnan(codes)],
                                  codes_obj[~np.isnan(codes_obj)])
    # numeric labels over a float arrow column: fast path declines, and
    # string-comparison semantics pick the exact textual match
    s2 = _IndexSource("c", np.array(["1", "1.0"]), "keep")
    fcol = pd.Series([1.0, 1.0], dtype="double[pyarrow]")
    assert s2._arrow_codes(fcol) is None
    scol = pd.Series(["1.0", "1"], dtype="str")
    assert s2.codes(pd.DataFrame({"c": scol})).tolist() == [1.0, 0.0]
