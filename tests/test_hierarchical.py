"""Hierarchical DCN-aware collectives (ISSUE 20): the two-level
histogram allreduce, the per-host data plane, and elastic
preemption-tolerant fits, proven on the simulated 8-device mesh
partitioned into virtual host groups (`parallel.mesh.host_mesh`).

Contracts:

- HOP PARITY: `psum_hierarchical` (intra-group reduce-scatter over
  "ici", inter-group allreduce over "dcn", allgather back) equals the
  flat psum BIT-EXACTLY on integer-valued payloads at every group shape
  {1x8, 2x4, 4x2}, and its per-hop byte counters obey
  dcn = ici / ici_size exactly — the cross-host hop carries only the
  inter-group fraction of the flat allreduce payload (the acceptance
  bound, also recorded in the committed `multihost` bench block).
- HOST-SHAPE INVARIANCE: DT/RF/xgboost fits and CV avgMetrics on host
  meshes match the 1-host-group fit at every tested shape (sampling is
  layout-invariant; remaining drift is float reduction order, the same
  tolerance contract as tests/test_multichip.py) — and the 1-host-group
  mesh reproduces the flat 8-device fit EXACTLY.
- PER-HOST DATA PLANE: `ChunkSource.host_view` partitions the chunk
  stream into contiguous per-group row ranges that reassemble the
  parent bit-exactly, chunk-layout-invariantly.
- ELASTIC FITS: killing a host group mid-fit (chaos hook at a
  checkpoint boundary) resumes from the round-level checkpoint on the
  surviving groups and finishes the same model as the uninterrupted
  fit, counting `elastic.resume`/`elastic.repartition`.
- Straggler attribution grows HOST lanes (`skew.host.*`), and the
  regression sentry judges the `multihost` sidecar block (vanished
  block, DCN-byte growth, lost parity, lost skew table).
"""

import os

import numpy as np
import pandas as pd
import pytest

from sml_tpu.conf import GLOBAL_CONF

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture()
def xy():
    rng = np.random.default_rng(11)
    n = 4096
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * 3 - X[:, 1] ** 2 + 0.5 * X[:, 2]
         + rng.normal(0, 0.2, n)).astype(np.float32)
    return X, y


@pytest.fixture()
def recording():
    prev = GLOBAL_CONF.get("sml.obs.enabled")
    GLOBAL_CONF.set("sml.obs.enabled", True)
    from sml_tpu import obs
    obs.reset()
    yield obs
    GLOBAL_CONF.set("sml.obs.enabled", bool(prev))


def _host(h):
    from sml_tpu.parallel import mesh as meshlib
    return meshlib.use_mesh(meshlib.host_mesh(h))


def _flat(w):
    from sml_tpu.parallel import mesh as meshlib
    return meshlib.use_mesh(meshlib.build_mesh(w))


def _frame(spark, X, y, label="label"):
    from sml_tpu.ml.feature import VectorAssembler
    pdf = pd.DataFrame({f"f{i}": X[:, i] for i in range(X.shape[1])})
    pdf[label] = y
    fdf = VectorAssembler(inputCols=[f"f{i}" for i in range(X.shape[1])],
                          outputCol="features") \
        .transform(spark.createDataFrame(pdf))
    fdf.cache()
    return fdf


# ------------------------------------------------------- host-mesh topology
def test_host_mesh_shapes_placement_and_partition():
    """`host_mesh(h)` declares the (dcn, ici) axes host-major, places
    every global row on exactly the device the flat mesh would, and
    `host_partition` splits row ranges contiguously with the remainder
    leading — the layout contract the whole data plane rides."""
    import jax

    from sml_tpu.parallel import mesh as meshlib

    assert len(jax.devices()) >= 8
    for h, per in ((1, 8), (2, 4), (4, 2), (8, 1)):
        m = meshlib.host_mesh(h)
        assert meshlib.is_hierarchical(m)
        assert dict(m.shape) == {"dcn": h, "ici": per}
        assert meshlib.data_width(m) == 8
        assert meshlib.row_axes(m) == ("dcn", "ici")
        # device d of the flat mesh sits at (d // per, d % per)
        flat = list(meshlib.build_mesh(8).devices.flat)
        grid = m.devices
        for d in range(8):
            assert grid[d // per][d % per] is flat[d]
        groups = meshlib.host_group_of(m)
        assert sorted(set(groups.values())) == list(range(h))
        # row-sharded placement identical to the flat mesh, shard by shard
        X = np.arange(64 * 3, dtype=np.float32).reshape(64, 3)
        with meshlib.use_mesh(m):
            arr, n_true = meshlib.shard_rows(X)
        with meshlib.use_mesh(meshlib.build_mesh(8)):
            ref, _ = meshlib.shard_rows(X)
        hb = {d.id: np.asarray(b)
              for d, b in meshlib.addressable_row_blocks(arr)}
        fb = {d.id: np.asarray(b)
              for d, b in meshlib.addressable_row_blocks(ref)}
        assert hb.keys() == fb.keys()
        for did in hb:
            np.testing.assert_array_equal(hb[did], fb[did])
    with pytest.raises(ValueError):
        meshlib.host_mesh(3)  # 3 groups do not divide 8 devices
    assert meshlib.host_partition(100, 3) == [(0, 34), (34, 67), (67, 100)]
    assert meshlib.host_partition(8, 8) == [(i, i + 1) for i in range(8)]


def test_host_groups_conf_knob_resolves_shape():
    from sml_tpu.parallel import mesh as meshlib
    GLOBAL_CONF.set("sml.mesh.hostGroups", 4)
    try:
        assert dict(meshlib.host_mesh().shape) == {"dcn": 4, "ici": 2}
    finally:
        GLOBAL_CONF.unset("sml.mesh.hostGroups")


# ------------------------------------------------ two-level psum bit parity
@pytest.mark.parametrize("h", [1, 2, 4])
def test_psum_hierarchical_bit_parity_and_hop_bytes(recording, h):
    """The two-level allreduce equals the flat psum bit-exactly on
    integer-valued payloads, and its per-hop byte statics obey
    dcn == ici / ici_size (the cross-host hop carries only the
    inter-group fraction) with the allgather return hop matching the
    dcn chunk — at every group shape."""
    import jax
    from jax.sharding import PartitionSpec as P

    from sml_tpu.parallel import collectives as coll
    from sml_tpu.parallel import mesh as meshlib

    obs = recording
    rng = np.random.default_rng(3)
    X = rng.integers(0, 100, size=(64, 7)).astype(np.float32)
    expect = X.reshape(8, 8, 7).sum(axis=0)  # exact: integer-valued f32

    mesh = meshlib.host_mesh(h)
    per = 8 // h
    spec = P(meshlib.row_spec_entry(mesh))

    def run(fn):
        f = meshlib.shard_map_compat(fn, mesh=mesh, in_specs=(spec,),
                                     out_specs=P())
        return np.asarray(jax.jit(f)(X))

    with meshlib.use_mesh(mesh):
        obs.reset()
        hier = run(lambda b: coll.psum_hierarchical(b, ici_size=per))
        hop = obs.RECORDER.counters()
        flat = run(lambda b: coll.psum(b, (meshlib.DCN_AXIS,
                                           meshlib.ICI_AXIS)))
    np.testing.assert_array_equal(hier, flat)
    np.testing.assert_array_equal(hier, expect)
    block_bytes = 8 * 7 * 4  # one device's (8, 7) f32 shard
    if per > 1:
        assert hop.get("collective.psum.ici") == 1
        assert hop.get("collective.psum.dcn") == 1
        assert hop.get("collective.psum_bytes.ici") == block_bytes
        assert hop.get("collective.psum_bytes.dcn") == block_bytes / per
        assert hop.get("collective.all_gather_bytes.ici") \
            == block_bytes / per
    else:
        # ici_size=1 degenerates to the flat psum over the dcn hop
        assert hop.get("collective.psum_bytes.dcn") == block_bytes
        assert "collective.psum_bytes.ici" not in hop


def test_psum_hierarchical_pads_non_divisible_payload(recording):
    """A payload whose flat size does not divide ici_size is zero-padded
    for the reduce-scatter and unpadded after the allgather — exact for
    sums, any shape."""
    import jax
    from jax.sharding import PartitionSpec as P

    from sml_tpu.parallel import collectives as coll
    from sml_tpu.parallel import mesh as meshlib

    mesh = meshlib.host_mesh(2)  # ici_size 4; 3*5=15 pads to 16
    rng = np.random.default_rng(5)
    X = rng.integers(0, 50, size=(24, 3, 5)).astype(np.float32)
    spec = P(meshlib.row_spec_entry(mesh))
    with meshlib.use_mesh(mesh):
        f = meshlib.shard_map_compat(
            lambda b: coll.psum_hierarchical(b, ici_size=4),
            mesh=mesh, in_specs=(spec,), out_specs=P())
        out = np.asarray(jax.jit(f)(X))
    np.testing.assert_array_equal(out, X.reshape(8, 3, 3, 5).sum(axis=0))


# -------------------------------------------------- fit parity across shapes
@pytest.mark.parametrize("kind", ["dt", "rf", "xgb"])
def test_fit_parity_host_shapes_vs_1host_and_flat(spark, xy, kind):
    """The same estimator fit at every host-group shape produces the
    same model as the 1-host-group fit (float reduction-order
    tolerance, the test_multichip contract), and the 1-host-group mesh
    reproduces the flat 8-device fit EXACTLY — the hierarchical path is
    a drop-in for the flat allreduce, not a different estimator."""
    from sml_tpu.ml.evaluation import RegressionEvaluator

    X, y = xy

    def factory():
        from sml_tpu.ml.regression import (DecisionTreeRegressor,
                                           RandomForestRegressor)
        from sml_tpu.xgboost import XgboostRegressor
        if kind == "dt":
            return DecisionTreeRegressor(labelCol="label", maxDepth=5,
                                         maxBins=16)
        if kind == "rf":
            return RandomForestRegressor(labelCol="label", maxDepth=4,
                                         numTrees=8, maxBins=16,
                                         subsamplingRate=0.9, seed=7)
        return XgboostRegressor(n_estimators=8, max_depth=4, max_bins=16,
                                learning_rate=0.3, subsample=0.8,
                                random_state=5)

    fdf = _frame(spark, X, y)

    def fit_predict(ctx):
        with ctx:
            model = factory().fit(fdf)
            pred = model.transform(fdf).toPandas()["prediction"].to_numpy()
            rmse = RegressionEvaluator(labelCol="label").evaluate(
                model.transform(fdf))
        return pred, rmse

    p_flat, rmse_flat = fit_predict(_flat(8))
    p1, rmse1 = fit_predict(_host(1))
    # 1 host group x 8 devices: same reduction topology as flat — exact
    np.testing.assert_array_equal(p1, p_flat)
    assert rmse1 == rmse_flat
    for h in (2, 4):
        ph, rmseh = fit_predict(_host(h))
        np.testing.assert_allclose(ph, p1, rtol=1e-4, atol=1e-4)
        assert abs(rmseh - rmse1) < 1e-4 * max(abs(rmse1), 1.0)


def test_cv_avgmetrics_parity_on_host_mesh(spark, xy):
    """Grid-fused CV (TrialDyn fused trials) over a host-partitioned
    mesh: fused elements ride the replicated-element branch (the trial
    axis stays 1 on a 2-axis row mesh) and avgMetrics match the flat
    8-device run within reduction-order tolerance."""
    from sml_tpu.ml import tree_impl
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder

    X, y = xy
    fdf = _frame(spark, X, y)
    rf = RandomForestRegressor(labelCol="label", maxBins=16, seed=7)
    grid = (ParamGridBuilder()
            .addGrid(rf.getParam("maxDepth"), [2, 4])
            .addGrid(rf.getParam("numTrees"), [3, 6]).build())
    cv = CrossValidator(estimator=rf, estimatorParamMaps=grid,
                        evaluator=RegressionEvaluator(labelCol="label"),
                        numFolds=3, parallelism=1, seed=13)
    GLOBAL_CONF.set("sml.cv.batchFolds", True)
    try:
        with _host(2):
            assert tree_impl._trial_axis_width(8, 4096) == 1
            m_host = cv.fit(fdf).avgMetrics
        with _flat(8):
            m_flat = cv.fit(fdf).avgMetrics
    finally:
        GLOBAL_CONF.unset("sml.cv.batchFolds")
    np.testing.assert_allclose(m_host, m_flat, rtol=1e-4, atol=1e-4)


# --------------------------------------------- per-hop byte economics
def test_dcn_bytes_bounded_by_inter_group_fraction(recording, xy):
    """ISSUE 20 acceptance: the DCN-hop psum bytes of a hierarchical
    fit are <= the inter-group fraction (1/ici_size) of the flat
    allreduce's bytes, exactly dcn == ici / ici_size per trace, with
    the allgather return hop the same size as the dcn chunk."""
    from sml_tpu.ml._tree_models import _fit_ensemble

    X, y = xy
    obs = recording

    def fit():
        return _fit_ensemble(X, y, categorical={}, max_depth=4,
                             max_bins=16, min_instances=1,
                             min_info_gain=0.0, n_trees=2, feature_k=None,
                             bootstrap=False, subsample=1.0, seed=3,
                             loss="squared")

    obs.reset()
    with _flat(8):
        fit()
    flat_bytes = obs.RECORDER.counters().get("collective.psum_bytes", 0.0)
    assert flat_bytes > 0
    for h, per in ((2, 4), (4, 2)):
        obs.reset()
        with _host(h):
            fit()
        c = obs.RECORDER.counters()
        ici_b = c.get("collective.psum_bytes.ici", 0.0)
        dcn_b = c.get("collective.psum_bytes.dcn", 0.0)
        ag_b = c.get("collective.all_gather_bytes.ici", 0.0)
        assert ici_b > 0 and dcn_b > 0
        assert dcn_b == ici_b / per  # exact: payload pads to ici_size
        assert ag_b == dcn_b
        # the acceptance bound vs the FLAT allreduce payload (1% slack
        # covers the flat path's extra scalar psums + padding)
        assert dcn_b <= flat_bytes / per * 1.01 + 1024


def test_hist_subtraction_halves_per_hop_payload(xy, recording):
    """The histogram-subtraction trick halves the below-root payload on
    BOTH hops of the hierarchical allreduce — the per-hop counters see
    the same saving the flat `collective.psum_bytes` counter does."""
    from sml_tpu.ml._tree_models import _fit_ensemble

    X, y = xy
    obs = recording
    volumes = {}
    try:
        for sub in (True, False):
            GLOBAL_CONF.set("sml.tree.histSubtraction", sub)
            obs.reset()
            # static params distinct from every other fit in this file:
            # per-hop counters are TRACE-time statics, so a program-cache
            # hit would record nothing
            with _host(2):
                _fit_ensemble(X, y, categorical={}, max_depth=5,
                              max_bins=24, min_instances=1,
                              min_info_gain=0.0, n_trees=3, feature_k=None,
                              bootstrap=False, subsample=1.0, seed=3,
                              loss="squared")
            c = obs.RECORDER.counters()
            volumes[sub] = (c.get("collective.psum_bytes.ici", 0.0),
                            c.get("collective.psum_bytes.dcn", 0.0))
    finally:
        GLOBAL_CONF.unset("sml.tree.histSubtraction")
    for hop in (0, 1):
        assert 0 < volumes[True][hop] < volumes[False][hop]


def test_hierarchical_knob_off_uses_flat_allreduce(xy, recording):
    """`sml.tree.hierarchicalAllreduce=false` on a host mesh routes the
    merge through ONE flat psum over both row axes (no per-hop
    counters), and the model still matches — the knob changes the wire
    pattern, never the estimator."""
    from sml_tpu.ml._tree_models import _fit_ensemble

    X, y = xy
    obs = recording

    def fit():
        # static params distinct from every other fit in this file: a
        # program-cache hit would skip the trace and record no counters
        return _fit_ensemble(X, y, categorical={}, max_depth=3,
                             max_bins=20, min_instances=1,
                             min_info_gain=0.0, n_trees=4, feature_k=None,
                             bootstrap=False, subsample=1.0, seed=3,
                             loss="squared")

    with _host(2):
        obs.reset()
        on = fit()
        c_on = obs.RECORDER.counters()
        GLOBAL_CONF.set("sml.tree.hierarchicalAllreduce", "false")
        try:
            obs.reset()
            off = fit()
            c_off = obs.RECORDER.counters()
        finally:
            GLOBAL_CONF.unset("sml.tree.hierarchicalAllreduce")
    assert c_on.get("collective.psum_bytes.ici", 0.0) > 0
    assert c_off.get("collective.psum_bytes.ici", 0.0) == 0
    assert c_off.get("collective.psum_bytes", 0.0) > 0
    pa = on.predict_margin(X[:512])
    pb = off.predict_margin(X[:512])
    np.testing.assert_allclose(pa, pb, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------ per-host data plane
def test_host_view_partitions_and_reassembles_bit_exact():
    """`ChunkSource.host_view` yields each group's contiguous global row
    range: the views concatenate back to the parent bit-exactly,
    whatever the parent's chunk size (chunk-layout invariance), and an
    uncounted source refuses a host view instead of guessing."""
    from sml_tpu.frame._chunks import ArrayChunkSource

    rng = np.random.default_rng(7)
    X = rng.normal(size=(100, 4)).astype(np.float32)
    y = rng.normal(size=100).astype(np.float32)
    for chunk_rows in (7, 33, 100):
        src = ArrayChunkSource(X, y, chunk_rows=chunk_rows)
        views = [src.host_view(g, 3) for g in range(3)]
        assert [(v.start, v.stop) for v in views] \
            == [(0, 34), (34, 67), (67, 100)]
        Xs = np.concatenate([np.concatenate([c[0] for c in v.chunks()])
                             for v in views])
        ys = np.concatenate([np.concatenate([c[1] for c in v.chunks()])
                             for v in views])
        np.testing.assert_array_equal(Xs, X)
        np.testing.assert_array_equal(ys, y)
        # re-iterable (the two-pass ingest contract) + fingerprinted
        again = np.concatenate([c[0] for c in views[1].chunks()])
        np.testing.assert_array_equal(again, X[34:67])
        fp = views[1].fingerprint()
        assert fp[0] == "host" and fp[2:] == (1, 3)
    src = ArrayChunkSource(X, y, chunk_rows=10)
    src.n_rows = None  # an uncounted stream (pre-sketch-pass)
    with pytest.raises(ValueError, match="counted"):
        src.host_view(0, 2)
    with pytest.raises(ValueError):
        ArrayChunkSource(X, y, chunk_rows=10).host_view(5, 3)


# ------------------------------------------------------------- elastic fits
def test_elastic_fit_resumes_after_host_kill(tmp_path, recording):
    """ISSUE 20 acceptance: a host group killed mid-fit (chaos hook at
    a checkpoint boundary) resumes via the round-level checkpoint on
    the surviving groups and finishes the same final model as the
    uninterrupted fit, with `elastic.resume`/`elastic.repartition`
    counted and the checkpoint dir cleared on success."""
    from sml_tpu.ct import HostPreempted, elastic_fit
    from sml_tpu.frame._chunks import ArrayChunkSource

    obs = recording
    rng = np.random.default_rng(11)
    n = 960  # bucket_rows(960, 8) == bucket_rows(960, 6) == 960:
    #          the padded shape survives the 4x2 -> 3x2 mesh resize
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X @ rng.normal(size=6) + 0.1 * rng.normal(size=n)) \
        .astype(np.float32)
    params = dict(n_trees=6, max_depth=3, max_bins=32, seed=7,
                  step_size=0.3, rounds_per_dispatch=2)

    ref = elastic_fit(ArrayChunkSource(X, y, chunk_rows=128),
                      str(tmp_path / "ref"), hosts=4, **params)

    killed = {"fired": False}

    def chaos(t_done):
        if not killed["fired"] and t_done >= 2:
            killed["fired"] = True
            raise HostPreempted(group=1)

    obs.reset()
    spec = elastic_fit(ArrayChunkSource(X, y, chunk_rows=128),
                       str(tmp_path / "el"), hosts=4,
                       on_checkpoint=chaos, **params)
    assert killed["fired"]
    assert len(spec.trees) == len(ref.trees) == 6
    p, pr = spec.predict_margin(X), ref.predict_margin(X)
    # resumed rounds ran on a 3x2 mesh: float reduction-order tolerance
    np.testing.assert_allclose(p, pr, rtol=1e-4, atol=1e-5)
    c = obs.RECORDER.counters()
    assert c.get("elastic.resume") == 1
    assert c.get("elastic.repartition") == 1
    assert not os.path.exists(str(tmp_path / "el"))  # cleared on success


def test_elastic_fit_gate_off_and_budget_exhausted(tmp_path):
    """With `sml.ct.elasticResume` off the preemption propagates; with
    the restart budget exhausted a repeatedly-dying fit stops resuming
    instead of shrinking to nothing."""
    from sml_tpu.ct import HostPreempted, elastic_fit
    from sml_tpu.frame._chunks import ArrayChunkSource

    rng = np.random.default_rng(2)
    X = rng.normal(size=(512, 4)).astype(np.float32)
    y = rng.normal(size=512).astype(np.float32)
    params = dict(n_trees=4, max_depth=2, max_bins=16, seed=3,
                  rounds_per_dispatch=2)

    def always_die(t_done):
        raise HostPreempted(group=0)

    GLOBAL_CONF.set("sml.ct.elasticResume", "false")
    try:
        with pytest.raises(HostPreempted):
            elastic_fit(ArrayChunkSource(X, y, chunk_rows=128),
                        str(tmp_path / "off"), hosts=2,
                        on_checkpoint=always_die, **params)
    finally:
        GLOBAL_CONF.unset("sml.ct.elasticResume")
    # every attempt makes checkpoint progress (the resumed remainder can
    # finish inside one dispatch, past the last chaos boundary), so the
    # budget path is pinned at 0: the gate is ON but no restart is
    # allowed — the first preemption must propagate through the
    # budget branch, not the gate branch
    GLOBAL_CONF.set("sml.ct.elasticMaxRestarts", 0)
    try:
        with pytest.raises(HostPreempted):
            elastic_fit(ArrayChunkSource(X, y, chunk_rows=128),
                        str(tmp_path / "budget"), hosts=4,
                        on_checkpoint=always_die, **params)
    finally:
        GLOBAL_CONF.unset("sml.ct.elasticMaxRestarts")


def test_moved_rows_accounting():
    from sml_tpu.ct._elastic import moved_rows
    # 4 -> 3 groups over 960 rows: group 0 keeps [0,240) of [0,320);
    # overlaps are 240+160+80 = 480 kept, 480 moved
    assert moved_rows(960, 4, 3) == 480
    assert moved_rows(100, 2, 2) == 0
    assert moved_rows(0, 4, 2) == 0


# ------------------------------------------------- multihost init satellites
def test_initialize_multihost_single_process_fast_path(monkeypatch):
    """num_processes absent or 1: returns False WITHOUT touching
    jax.distributed (the fast path a single-host fit rides)."""
    import jax

    from sml_tpu.parallel import collectives

    def boom(**kw):
        raise AssertionError("jax.distributed.initialize must not be "
                             "called on the single-process fast path")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    assert collectives.initialize_multihost() is False
    assert collectives.initialize_multihost(num_processes=1) is False
    assert collectives.initialize_multihost("127.0.0.1:1",
                                            num_processes=0) is False


def test_initialize_multihost_wraps_failure_typed(monkeypatch):
    """A bring-up failure surfaces as `MultihostInitError` carrying the
    peer config (coordinator / num_processes / process_id), chained to
    the runtime's original exception — and the timeout kwarg is passed
    when the pinned jax supports it."""
    import jax

    from sml_tpu.parallel import collectives

    seen = {}

    def dying(coordinator_address=None, num_processes=None,
              process_id=None, initialization_timeout=None):
        seen.update(coordinator_address=coordinator_address,
                    num_processes=num_processes, process_id=process_id,
                    initialization_timeout=initialization_timeout)
        raise RuntimeError("coordination service unreachable")

    monkeypatch.setattr(jax.distributed, "initialize", dying)
    with pytest.raises(collectives.MultihostInitError) as ei:
        collectives.initialize_multihost("10.0.0.1:8476", num_processes=2,
                                         process_id=1, timeout_s=7)
    err = ei.value
    assert err.coordinator == "10.0.0.1:8476"
    assert err.num_processes == 2 and err.process_id == 1
    assert isinstance(err.__cause__, RuntimeError)
    assert seen["initialization_timeout"] == 7
    assert "10.0.0.1:8476" in str(err)


# --------------------------------------------------- host-level skew lanes
def test_skew_tracker_host_lanes_and_report(recording):
    """`SkewTracker.note(hosts=...)` rolls per-device timings up to host
    groups (a group's compute is its slowest member's), names the
    slowest host in the entry, the note event, and the aggregate
    straggler report, and lands skew.host.compute/.wait spans."""
    obs = recording
    tracker = obs.SKEW
    tracker.reset()
    e = tracker.note("hier_probe", [1.0, 2.0, 1.5, 0.5],
                     devices=[0, 1, 2, 3], hosts=[0, 0, 1, 1])
    assert e["host_ids"] == [0, 1]
    assert e["per_host_compute_s"] == [2.0, 1.5]
    assert e["slowest_host"] == 0
    rep = tracker.straggler_report()
    assert rep["slowest_host"] == 0 and rep["n_hosts"] == 2
    assert rep["per_host"][1]["wait_s"] == pytest.approx(0.5)
    assert rep["host_skew_ratio"] == pytest.approx(2.0 / 1.75, rel=1e-3)
    names = [ev.name for ev in obs.RECORDER.events()]
    assert "skew.host.compute" in names and "skew.host.wait" in names
    # host-free notes still work and the report omits the host block
    tracker.reset()
    tracker.note("flat_probe", [1.0, 1.2])
    assert "slowest_host" not in tracker.straggler_report()
    with pytest.raises(ValueError):
        tracker.note("bad", [1.0, 2.0], hosts=[0])


# ------------------------------------------------- regression-sentry judge
def _mh_entry(**over):
    e = {"hosts": 2, "per_host": 4, "seconds": 1.0, "psum_ici": 5,
         "psum_dcn": 5, "psum_bytes_ici": 9408.0, "psum_bytes_dcn": 2352.0,
         "parity_ok": True, "slowest_host": 0,
         "host_skew": [{"host": 0, "compute_ms": 1.0},
                       {"host": 1, "compute_ms": 1.2}]}
    e.update(over)
    return e


def _sidecar(entry=None, block=True):
    doc = {"legs": {}}
    if block:
        doc["multihost"] = {"shapes": [entry or _mh_entry()]}
    return doc


def test_regress_judges_multihost_block():
    """obs/regress.py judges the `multihost` sidecar block: a vanished
    block or shape, DCN-byte growth past the 1% static tolerance, a
    flipped parity proof, and a lost host-skew table are regressions;
    an identical candidate and a BENCH_r0x driver record are clean."""
    from sml_tpu.obs import regress

    base = regress.normalize(_sidecar())
    ok = regress.compare(base, regress.normalize(_sidecar()))
    assert ok["ok"]

    res = regress.compare(base, regress.normalize(_sidecar(block=False)))
    assert not res["ok"]
    assert any(f["kind"] == "missing-multihost-block"
               for f in res["regressions"])
    # driver records can never carry the block: exempt
    rec = regress.normalize({"parsed": {}, "tail": ""})
    assert rec["shape"] == "record"
    assert regress.compare(base, rec)["ok"]

    grew = regress.normalize(_sidecar(_mh_entry(psum_bytes_dcn=9408.0)))
    res = regress.compare(base, grew)
    assert not res["ok"]
    assert any(f["kind"] == "multihost-collective"
               and "psum_bytes_dcn" in f["key"] for f in res["regressions"])

    flipped = regress.normalize(_sidecar(_mh_entry(parity_ok=False)))
    res = regress.compare(base, flipped)
    assert not res["ok"]
    assert any(f["kind"] == "multihost-parity" for f in res["regressions"])

    skewless = regress.normalize(_sidecar(_mh_entry(host_skew=None)))
    res = regress.compare(base, skewless)
    assert not res["ok"]
    assert any(f["kind"] == "multihost-skew" for f in res["regressions"])

    reshaped = regress.normalize(
        {"legs": {}, "multihost": {"shapes": [_mh_entry(hosts=4)]}})
    res = regress.compare(base, reshaped)
    assert not res["ok"]
    assert any(f["kind"] == "missing-multihost-shape"
               for f in res["regressions"])
