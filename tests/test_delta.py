import os

import pandas as pd
import pytest

import sml_tpu.frame.functions as F
from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.delta import DeltaTable


def _df(spark, n=100, extra=False):
    pdf = pd.DataFrame({"id": range(n), "v": [float(i) * 2 for i in range(n)]})
    if extra:
        pdf["w"] = "x"
    return spark.createDataFrame(pdf, numPartitions=4)


def test_delta_write_read(spark, tmp_path):
    p = str(tmp_path / "t")
    _df(spark).write.format("delta").mode("overwrite").save(p)
    assert os.path.isdir(os.path.join(p, "_delta_log"))
    back = spark.read.format("delta").load(p)
    assert back.count() == 100


def test_delta_versioning_time_travel(spark, tmp_path):
    p = str(tmp_path / "t")
    _df(spark, 100).write.format("delta").save(p)
    _df(spark, 50).write.format("delta").mode("overwrite").save(p)
    latest = spark.read.format("delta").load(p)
    assert latest.count() == 50
    v0 = spark.read.format("delta").option("versionAsOf", 0).load(p)
    assert v0.count() == 100


def test_delta_history(spark, tmp_path):
    p = str(tmp_path / "t")
    _df(spark).write.format("delta").save(p)
    _df(spark).write.format("delta").mode("append").save(p)
    h = DeltaTable.forPath(spark, p).history().toPandas()
    assert h["version"].tolist() == [1, 0]
    h2 = spark.sql(f"DESCRIBE HISTORY delta.`{p}`").toPandas()
    assert len(h2) == 2


def test_delta_append_and_schema_enforcement(spark, tmp_path):
    p = str(tmp_path / "t")
    _df(spark).write.format("delta").save(p)
    _df(spark).write.format("delta").mode("append").save(p)
    assert spark.read.format("delta").load(p).count() == 200
    # schema change without mergeSchema → error
    with pytest.raises(ValueError, match="[Ss]chema"):
        _df(spark, 10, extra=True).write.format("delta").mode("append").save(p)
    # with mergeSchema → ok (ML 05L answer path)
    _df(spark, 10, extra=True).write.format("delta").mode("append") \
        .option("mergeSchema", "true").save(p)
    assert spark.read.format("delta").load(p).count() == 210


def test_delta_overwrite_schema(spark, tmp_path):
    p = str(tmp_path / "t")
    _df(spark).write.format("delta").save(p)
    with pytest.raises(ValueError, match="overwriteSchema"):
        _df(spark, 10, extra=True).write.format("delta").mode("overwrite").save(p)
    _df(spark, 10, extra=True).write.format("delta").mode("overwrite") \
        .option("overwriteSchema", "true").save(p)
    assert "w" in spark.read.format("delta").load(p).columns


def test_delta_partitioned(spark, tmp_path):
    p = str(tmp_path / "t")
    df = _df(spark).withColumn("part", (F.col("id") % 3).cast("int"))
    df.write.format("delta").partitionBy("part").mode("overwrite").save(p)
    back = spark.read.format("delta").load(p)
    assert back.count() == 100
    assert set(back.toPandas()["part"]) == {0, 1, 2}


def test_delta_vacuum_retention_check(spark, tmp_path):
    p = str(tmp_path / "t")
    _df(spark).write.format("delta").save(p)
    _df(spark, 50).write.format("delta").mode("overwrite").save(p)
    dt = DeltaTable.forPath(spark, p)
    GLOBAL_CONF.set("sml.delta.retentionDurationCheck.enabled", True)
    with pytest.raises(ValueError, match="retention"):
        dt.vacuum(0)
    GLOBAL_CONF.set("sml.delta.retentionDurationCheck.enabled", False)
    dt.vacuum(0)
    GLOBAL_CONF.set("sml.delta.retentionDurationCheck.enabled", True)
    # old files gone → v0 unreadable, latest still fine
    assert spark.read.format("delta").load(p).count() == 50
    parquets = [f for _r, _d, fs in os.walk(p) for f in fs if f.endswith(".parquet")]
    assert len(parquets) == 4  # only the live version's 4 part-files remain


def test_save_as_table(spark, tmp_path):
    df = _df(spark)
    df.write.format("delta").mode("overwrite").saveAsTable("t_test")
    back = spark.table("t_test")
    assert back.count() == 100
    assert spark.catalog.tableExists("t_test")


def test_sql_version_as_of(spark, tmp_path):
    """SELECT-level time travel (`ML 00c:184-209`): VERSION AS OF,
    TIMESTAMP AS OF, and the delta.`path@vN` shorthand."""
    import pandas as pd
    p = str(tmp_path / "tt")
    spark.createDataFrame(pd.DataFrame({"x": [1, 2]})) \
        .write.format("delta").mode("overwrite").save(p)
    spark.createDataFrame(pd.DataFrame({"x": [10, 20, 30]})) \
        .write.format("delta").mode("overwrite").save(p)

    v0 = spark.sql(f"SELECT * FROM delta.`{p}` VERSION AS OF 0").toPandas()
    assert sorted(v0["x"].tolist()) == [1, 2]
    v1 = spark.sql(f"SELECT * FROM delta.`{p}` VERSION AS OF 1").toPandas()
    assert sorted(v1["x"].tolist()) == [10, 20, 30]
    sh = spark.sql(f"SELECT count(*) AS n FROM delta.`{p}@v0`").toPandas()
    assert int(sh["n"].iloc[0]) == 2

    hist = spark.sql(f"DESCRIBE HISTORY delta.`{p}`").toPandas()
    ts = str(hist["timestamp"].max())
    vt = spark.sql(
        f"SELECT * FROM delta.`{p}` TIMESTAMP AS OF '{ts}'").toPandas()
    assert sorted(vt["x"].tolist()) == [10, 20, 30]


def test_drop_recreate_invalidates_time_travel_cache(spark):
    """DROP TABLE then recreate at the same warehouse path must not serve
    pre-drop snapshots from the session SQL store (ADVICE r3): the cached
    `_tt_*` relations carry path-keyed tokens that survive a name-only
    invalidation."""
    import pandas as pd
    spark.createDataFrame(pd.DataFrame({"x": [1, 2]})) \
        .write.format("delta").mode("overwrite").saveAsTable("tt_cycle")
    p = spark.catalog._table_path("tt_cycle")
    old = spark.sql(
        f"SELECT * FROM delta.`{p}` VERSION AS OF 0").toPandas()
    assert sorted(old["x"].tolist()) == [1, 2]
    spark.sql("DROP TABLE tt_cycle")
    spark.createDataFrame(pd.DataFrame({"x": [7, 8, 9]})) \
        .write.format("delta").mode("overwrite").saveAsTable("tt_cycle")
    fresh = spark.sql(
        f"SELECT * FROM delta.`{p}` VERSION AS OF 0").toPandas()
    assert sorted(fresh["x"].tolist()) == [7, 8, 9]
    spark.sql("DROP TABLE tt_cycle")
