"""Notebook replay harness: each lesson's cell sequence end-to-end.

The reference's own test strategy is "the notebooks are the integration
tests" (`SML/Includes/Classroom-Setup.py:83-92`): a lesson passes when its
cells run top to bottom and the printed metrics look right. This module
replays every lesson ML 00b–ML 14 plus the electives as one
assertion-bearing run each, using course-parity API names against the
generated datasets (VERDICT r2 #5). Unit tests elsewhere cover the pieces;
these prove each lesson COMPOSES.
"""

import os

import numpy as np
import pandas as pd
import pytest

from sml_tpu import functions as F
from sml_tpu.courseware import make_airbnb_dataset, make_movielens_dataset


@pytest.fixture(scope="module")
def raw_df(spark):
    """The ML 01 entry point: price as a '$1,234.00'-style string, nulls in
    the review/bath/bed columns — the raw sf-listings shape."""
    pdf = make_airbnb_dataset(n=4000, seed=42)
    rng = np.random.default_rng(0)
    raw = pdf.copy()
    raw["price"] = raw["price"].map(lambda v: f"${v:,.2f}")
    raw.loc[rng.random(len(raw)) < 0.05, "bedrooms"] = np.nan
    raw.loc[rng.random(len(raw)) < 0.05, "review_scores_rating"] = np.nan
    return spark.createDataFrame(raw)


@pytest.fixture(scope="module")
def clean_dir(spark, raw_df, tmp_path_factory):
    """ML 01's output: the cleansed Delta table every later lesson reads."""
    out = str(tmp_path_factory.mktemp("lessons") / "airbnb-clean")
    fixed_price_df = raw_df.withColumn(
        "price", F.translate(F.col("price"), "$,", "").cast("double"))
    pos_prices_df = fixed_price_df.filter(F.col("price") > 0)
    min_nights_df = pos_prices_df.filter(F.col("minimum_nights") <= 365)
    impute_cols = ["bedrooms", "bathrooms", "review_scores_rating"]
    doubles_df = min_nights_df
    for c in impute_cols:
        doubles_df = doubles_df.withColumn(
            c + "_na", F.when(F.col(c).isNull(), 1.0).otherwise(0.0))
    from sml_tpu.ml.feature import Imputer
    imputer = Imputer(strategy="median", inputCols=impute_cols,
                      outputCols=impute_cols)
    imputed_df = imputer.fit(doubles_df).transform(doubles_df)
    imputed_df.write.format("delta").mode("overwrite").save(out)
    return out


# ---------------------------------------------------------------- ML 00b / 00c
def test_ml00b_spark_review(spark, raw_df):
    """select / filter / groupBy / orderBy / cache / SQL view (`ML 00b`)."""
    df = raw_df.select("room_type", "bedrooms", "price")
    df.cache()
    assert df.count() == 4000
    counts = (df.groupBy("room_type").count()
              .orderBy(F.col("count").desc()).toPandas())
    assert counts["count"].iloc[0] == counts["count"].max()
    df.createOrReplaceTempView("listings_view")
    top = spark.sql(
        "SELECT room_type, count(*) AS n FROM listings_view "
        "GROUP BY room_type ORDER BY n DESC").toPandas()
    assert sorted(top["n"].tolist(), reverse=True) == top["n"].tolist()


def test_ml00c_delta_review(spark, tmp_path):
    """Delta write → append → history → versionAsOf → vacuum guard."""
    p = str(tmp_path / "delta-review")
    df1 = spark.createDataFrame(pd.DataFrame({"id": [1, 2], "v": [1.0, 2.0]}))
    df1.write.format("delta").mode("overwrite").save(p)
    spark.createDataFrame(pd.DataFrame({"id": [3], "v": [3.0]})) \
        .write.format("delta").mode("append").save(p)
    from sml_tpu.delta.table import DeltaTable
    hist = DeltaTable.forPath(spark, p).history().toPandas()
    assert len(hist) == 2
    v0 = spark.read.format("delta").option("versionAsOf", 0).load(p)
    assert v0.count() == 2
    assert spark.read.format("delta").load(p).count() == 3
    with pytest.raises(Exception, match="retentionDurationCheck|retention"):
        DeltaTable.forPath(spark, p).vacuum(0)


# --------------------------------------------------------------------- ML 01
def test_ml00L_dedup_lab(spark, tmp_path):
    """Lab ML 00L end-to-end (`Labs/ML 00L:30-91`): case/format-insensitive
    dedup of 103k→100k records, 8-part parquet write, validated against the
    course's OWN hardcoded Spark hash constants — the only Spark-computed
    ground truth in the image. Passing means our Murmur3 hash() and the
    whole frame path reproduce Spark's answers bit-for-bit."""
    from sml_tpu import courseware as cw

    source_file = str(tmp_path / "people-with-dups.txt")
    cw.make_dedup_dataset().to_csv(source_file, index=False, sep=":")
    dest_file = str(tmp_path / "people.parquet")

    # dropDuplicates introduces a shuffle; the lab reduces post-shuffle
    # partitions to get the required 8 part files (Solutions/Labs/ML 00L)
    old = spark.conf.get("spark.sql.shuffle.partitions")
    spark.conf.set("spark.sql.shuffle.partitions", 8)
    try:
        df = (spark.read
              .option("header", "true")
              .option("inferSchema", "true")
              .option("sep", ":")
              .csv(source_file))
        deduped_df = (df
                      .select(F.col("*"),
                              F.lower(F.col("firstName")).alias("lcFirstName"),
                              F.lower(F.col("lastName")).alias("lcLastName"),
                              F.lower(F.col("middleName")).alias("lcMiddleName"),
                              F.translate(F.col("ssn"), "-", "").alias("ssnNums"))
                      .dropDuplicates(["lcFirstName", "lcMiddleName",
                                       "lcLastName", "ssnNums", "gender",
                                       "birthDate", "salary"])
                      .drop("lcFirstName", "lcMiddleName", "lcLastName",
                            "ssnNums"))
        deduped_df.write.mode("overwrite").parquet(dest_file)
    finally:
        spark.conf.set("spark.sql.shuffle.partitions", old)

    part_files = len([f for f in os.listdir(dest_file)
                      if f.endswith(".parquet")])
    final_df = spark.read.parquet(dest_file)
    final_count = final_df.count()

    results = cw.TestResults()
    assert results.validate_your_answer(
        "01 Parquet File Exists", 1276280174, part_files)
    assert results.validate_your_answer(
        "02 Expected 100000 Records", 972882115, final_count)
    assert results.all_passed
    # the original data formats were preserved (lab requirement): upper-case
    # name variants and both ssn formats survive in the kept records
    out = final_df.toPandas()
    assert out["firstName"].str.fullmatch(r"(PERSON|Person)\d+").all()
    assert set(out.columns) == {"firstName", "middleName", "lastName",
                                "gender", "birthDate", "salary", "ssn"}


def test_ml01_data_cleansing(spark, raw_df, clean_dir):
    """The cleansing chain produced a numeric, imputed, flagged table."""
    cleaned = spark.read.format("delta").load(clean_dir)
    pdf = cleaned.toPandas()
    assert pdf["price"].dtype == np.float64 and (pdf["price"] > 0).all()
    assert "bedrooms_na" in pdf.columns
    assert pdf["bedrooms"].notna().all()  # imputed in place
    assert set(pdf["bedrooms_na"].unique()) <= {0.0, 1.0}
    assert pdf["bedrooms_na"].sum() > 0  # the na flags recorded something


# ---------------------------------------------------------------- ML 02 / 03
def test_ml02_linear_regression_one_feature(spark, clean_dir):
    """randomSplit(seed=42) → LR on bedrooms → beats the mean baseline
    (`ML 02:155` states LR must beat predicting the average price)."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    vec = VectorAssembler(inputCols=["bedrooms"], outputCol="features")
    lr = LinearRegression(featuresCol="features", labelCol="price")
    lr_model = lr.fit(vec.transform(train_df))
    preds = lr_model.transform(vec.transform(test_df))
    ev = RegressionEvaluator(predictionCol="prediction", labelCol="price",
                             metricName="rmse")
    rmse = ev.evaluate(preds)
    mean_price = train_df.toPandas()["price"].mean()
    base = preds.withColumn("prediction", F.lit(float(mean_price)))
    assert rmse < ev.evaluate(base)  # the course's stated ordering
    assert lr_model.coefficients.toArray().shape == (1,)
    assert np.isfinite(lr_model.intercept)


def test_ml03_pipeline_save_load(spark, clean_dir, tmp_path):
    """Full featurization pipeline, persisted and reloaded (`ML 03`)."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.base import PipelineModel
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import (OneHotEncoder, StringIndexer,
                                    VectorAssembler)
    from sml_tpu.ml.regression import LinearRegression
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    cat = ["neighbourhood_cleansed", "room_type"]
    idx = [c + "Index" for c in cat]
    ohe = [c + "OHE" for c in cat]
    num = ["bedrooms", "accommodates", "minimum_nights"]
    pipe = Pipeline(stages=[
        StringIndexer(inputCols=cat, outputCols=idx, handleInvalid="skip"),
        OneHotEncoder(inputCols=idx, outputCols=ohe),
        VectorAssembler(inputCols=ohe + num, outputCol="features"),
        LinearRegression(labelCol="price")])
    model = pipe.fit(train_df)
    path = str(tmp_path / "lr-pipeline-model")
    model.write().overwrite().save(path)
    loaded = PipelineModel.load(path)
    ev = RegressionEvaluator(labelCol="price")
    r1 = ev.evaluate(model.transform(test_df))
    r2 = ev.evaluate(loaded.transform(test_df))
    assert abs(r1 - r2) < 1e-9
    assert 0 < r1 < 200


# ---------------------------------------------------------------- ML 04 / 05
def test_ml04_mlflow_tracking(spark, clean_dir, tmp_path):
    """start_run → log_param/metric/model → search_runs (`ML 04`)."""
    from sml_tpu import tracking as mlflow
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    mlflow.set_experiment("ml04")
    df = spark.read.format("delta").load(clean_dir)
    train_df, _ = df.randomSplit([.8, .2], seed=42)
    fdf = VectorAssembler(inputCols=["bedrooms"],
                          outputCol="features").transform(train_df)
    with mlflow.start_run(run_name="lr-single") as run:
        model = LinearRegression(labelCol="price").fit(fdf)
        mlflow.log_param("label", "price")
        mlflow.log_metric("rmse", float(model.summary.rootMeanSquaredError))
        mlflow.spark.log_model(model, "model")
    runs = mlflow.search_runs()
    assert len(runs) >= 1
    got = mlflow.get_run(run.info.run_id)
    assert got.data.params["label"] == "price"
    assert got.data.metrics["rmse"] > 0


def test_ml05_model_registry(spark, clean_dir, tmp_path):
    """Register → stage transition → load-by-stage → predict (`ML 05`)."""
    from sml_tpu import tracking as mlflow
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    mlflow.set_experiment("ml05")
    df = spark.read.format("delta").load(clean_dir)
    fdf = VectorAssembler(inputCols=["bedrooms"],
                          outputCol="features").transform(df)
    with mlflow.start_run() as run:
        model = LinearRegression(labelCol="price").fit(fdf)
        mlflow.spark.log_model(model, "model")
    name = "ml05_lr"
    mv = mlflow.register_model(f"runs:/{run.info.run_id}/model", name)
    client = mlflow.tracking.MlflowClient()
    client.transition_model_version_stage(name, mv.version,
                                          stage="Production")
    loaded = mlflow.spark.load_model(f"models:/{name}/Production")
    out = loaded.transform(fdf).toPandas()
    assert "prediction" in out.columns and np.isfinite(out["prediction"]).all()


def test_ml05L_registry_with_delta_time_travel(spark, clean_dir, tmp_path):
    """The lab's flow: model v1 on delta v0 → mergeSchema adds a column →
    model v2 → versionAsOf reproduces v1's training data (`ML 05L`)."""
    from sml_tpu import tracking as mlflow
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    mlflow.set_experiment("ml05L")
    p = str(tmp_path / "delta-lab")
    df = spark.read.format("delta").load(clean_dir)
    df.select("bedrooms", "accommodates", "price") \
        .write.format("delta").mode("overwrite").save(p)

    def fit_on(frame, cols):
        fdf = VectorAssembler(inputCols=cols,
                              outputCol="features").transform(frame)
        return LinearRegression(labelCol="price").fit(fdf)

    name = "ml05L_lr"
    with mlflow.start_run() as r1:
        m1 = fit_on(spark.read.format("delta").load(p), ["bedrooms"])
        mlflow.spark.log_model(m1, "model")
    mlflow.register_model(f"runs:/{r1.info.run_id}/model", name)

    # schema evolution: add a column with mergeSchema, retrain, re-register
    df.select("bedrooms", "accommodates", "price") \
        .withColumn("log_price", F.log(F.col("price"))) \
        .write.format("delta").mode("overwrite") \
        .option("mergeSchema", "true").save(p)
    with mlflow.start_run() as r2:
        m2 = fit_on(spark.read.format("delta").load(p),
                    ["bedrooms", "accommodates"])
        mlflow.spark.log_model(m2, "model")
    mv2 = mlflow.register_model(f"runs:/{r2.info.run_id}/model", name)
    assert int(mv2.version) == 2
    # time travel reproduces the v1 training frame (no log_price column)
    v0 = spark.read.format("delta").option("versionAsOf", 0).load(p)
    assert "log_price" not in v0.columns
    assert "log_price" in spark.read.format("delta").load(p).columns


# ---------------------------------------------------------------- ML 06 / 07
def test_ml06_decision_tree(spark, clean_dir):
    """maxBins failure on high-cardinality categoricals, the fix, and
    featureImportances (`ML 06:91-154`)."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.feature import StringIndexer, VectorAssembler
    from sml_tpu.ml.regression import DecisionTreeRegressor
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    cat = ["neighbourhood_cleansed", "room_type", "property_type"]
    idx = [c + "Index" for c in cat]
    stages = [StringIndexer(inputCols=cat, outputCols=idx,
                            handleInvalid="skip"),
              VectorAssembler(inputCols=idx + ["bedrooms", "accommodates"],
                              outputCol="features")]
    dt_small = DecisionTreeRegressor(labelCol="price", maxBins=2)
    with pytest.raises(Exception, match="maxBins"):
        Pipeline(stages=stages + [dt_small]).fit(train_df)
    dt = DecisionTreeRegressor(labelCol="price", maxBins=40)
    model = Pipeline(stages=stages + [dt]).fit(train_df)
    imp = model.stages[-1].featureImportances.toArray()
    assert imp.shape == (5,) and abs(imp.sum() - 1.0) < 1e-6
    out = model.transform(test_df).toPandas()
    assert np.isfinite(out["prediction"]).all()


def test_ml07_random_forest_cv(spark, clean_dir):
    """RF grid CV with parallelism, best model beats a single tree
    (`ML 07:102-171`)."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import StringIndexer, VectorAssembler
    from sml_tpu.ml.regression import (DecisionTreeRegressor,
                                       RandomForestRegressor)
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    stages = [StringIndexer(inputCols=["room_type"],
                            outputCols=["room_typeIndex"],
                            handleInvalid="skip"),
              VectorAssembler(
                  inputCols=["room_typeIndex", "bedrooms", "accommodates",
                             "number_of_reviews"], outputCol="features")]
    feat_train = Pipeline(stages=stages).fit(train_df).transform(train_df)
    feat_test = Pipeline(stages=stages).fit(train_df).transform(test_df)
    rf = RandomForestRegressor(labelCol="price", seed=42)
    grid = (ParamGridBuilder()
            .addGrid(rf.getParam("maxDepth"), [2, 5])
            .addGrid(rf.getParam("numTrees"), [5, 10]).build())
    ev = RegressionEvaluator(labelCol="price")
    cv = CrossValidator(estimator=rf, estimatorParamMaps=grid, evaluator=ev,
                        numFolds=3, parallelism=4, seed=42)
    cv_model = cv.fit(feat_train)
    assert len(cv_model.avgMetrics) == 4
    rmse_rf = ev.evaluate(cv_model.bestModel.transform(feat_test))
    dt = DecisionTreeRegressor(labelCol="price", maxDepth=2, maxBins=40)
    rmse_dt = ev.evaluate(dt.fit(feat_train).transform(feat_test))
    assert rmse_rf <= rmse_dt * 1.05  # RF (tuned) at least matches a stump


# -------------------------------------------------------------------- ML 08
def test_ml08_hyperopt(spark, clean_dir):
    """fmin/tpe/hp search over RF params, course budget (`ML 08:146`)."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.tune import STATUS_OK, Trials, fmin, hp, tpe
    df = spark.read.format("delta").load(clean_dir)
    train_df, _ = df.randomSplit([.8, .2], seed=42)
    fdf = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                          outputCol="features").transform(train_df)
    ev = RegressionEvaluator(labelCol="price")

    def objective(params):
        m = RandomForestRegressor(labelCol="price", seed=42,
                                  maxDepth=int(params["max_depth"]),
                                  numTrees=int(params["num_trees"])).fit(fdf)
        return {"loss": ev.evaluate(m.transform(fdf)), "status": STATUS_OK}

    space = {"max_depth": hp.quniform("max_depth", 2, 5, 1),
             "num_trees": hp.quniform("num_trees", 5, 10, 5)}
    trials = Trials()
    best = fmin(objective, space, algo=tpe, max_evals=4, trials=trials,
                rstate=np.random.RandomState(42))
    assert {"max_depth", "num_trees"} <= set(best)
    assert len(trials.trials) == 4


# ---------------------------------------------------------------- ML 09 / 10
def test_ml09_automl(spark, clean_dir):
    from sml_tpu import automl
    df = spark.read.format("delta").load(clean_dir)
    train_df, _ = df.randomSplit([.8, .2], seed=42)
    summary = automl.regress(train_df.select("bedrooms", "accommodates",
                                             "price"),
                             target_col="price", timeout_minutes=1,
                             max_trials=3)
    assert summary.best_trial is not None
    assert np.isfinite(summary.best_trial.metrics["val_rmse"])


def test_ml10_feature_store(spark, clean_dir, tmp_path):
    from sml_tpu import tracking as mlflow
    from sml_tpu.feature_store import FeatureLookup, FeatureStoreClient
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    os.environ["SML_FEATURE_STORE_DIR"] = str(tmp_path / "fs")
    fs = FeatureStoreClient()
    df = spark.read.format("delta").load(clean_dir)
    pdf = df.toPandas().reset_index().rename(columns={"index": "listing_id"})
    feats = spark.createDataFrame(
        pdf[["listing_id", "bedrooms", "accommodates"]])
    fs.create_table(name="lessons_fs.features", primary_keys=["listing_id"],
                    df=feats, description="airbnb features")
    labels = spark.createDataFrame(pdf[["listing_id", "price"]])
    training_set = fs.create_training_set(
        labels, [FeatureLookup(table_name="lessons_fs.features",
                               lookup_key="listing_id")],
        label="price")
    tdf = training_set.load_df()
    from sml_tpu.ml import Pipeline
    with mlflow.start_run() as run:
        # log the WHOLE pipeline so score_batch can go raw columns → pred
        model = Pipeline(stages=[
            VectorAssembler(inputCols=["bedrooms", "accommodates"],
                            outputCol="features"),
            LinearRegression(labelCol="price")]).fit(tdf)
        fs.log_model(model, "model", training_set=training_set,
                     registered_model_name="lessons_fs_model")
    scored = fs.score_batch(f"runs:/{run.info.run_id}/model", labels)
    out = scored.toPandas()
    assert "prediction" in out.columns and len(out) == len(pdf)


# -------------------------------------------------------------------- ML 11
def test_ml11_xgboost(spark, clean_dir):
    """Log-price boosted trees beat the linear model (`ML 11`)."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import StringIndexer, VectorAssembler
    from sml_tpu.xgboost import XgboostRegressor
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    log_train = train_df.withColumn("label", F.log(F.col("price")))
    log_test = test_df.withColumn("label", F.log(F.col("price")))
    stages = [StringIndexer(inputCols=["room_type"],
                            outputCols=["room_typeIndex"],
                            handleInvalid="skip"),
              VectorAssembler(inputCols=["room_typeIndex", "bedrooms",
                                         "accommodates"],
                              outputCol="features")]
    xgb = XgboostRegressor(n_estimators=20, max_depth=4, learning_rate=0.2,
                           random_state=42)
    model = Pipeline(stages=stages + [xgb]).fit(log_train)
    preds = model.transform(log_test).withColumn(
        "prediction", F.exp(F.col("prediction")))
    rmse = RegressionEvaluator(labelCol="price").evaluate(preds)
    assert 0 < rmse < 200


# ---------------------------------------------------------------- ML 12 / 13
def test_ml12_pandas_udf_inference(spark, clean_dir, tmp_path):
    """Load-once scoring through mapInPandas and the pyfunc spark_udf
    (`ML 12:101-143`)."""
    from sml_tpu import tracking as mlflow
    from sml_tpu.ml import DeviceScorer, Pipeline
    from sml_tpu.ml.feature import StringIndexer, VectorAssembler
    from sml_tpu.ml.regression import RandomForestRegressor
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    model = Pipeline(stages=[
        StringIndexer(inputCols=["room_type"], outputCols=["room_typeIndex"],
                      handleInvalid="skip"),
        VectorAssembler(inputCols=["room_typeIndex", "bedrooms",
                                   "accommodates"], outputCol="features"),
        RandomForestRegressor(labelCol="price", numTrees=5, maxDepth=4,
                              seed=42)]).fit(train_df)
    scorer = DeviceScorer(model)

    def predict(iterator):
        for features in iterator:
            yield pd.DataFrame({"prediction": scorer(features)})

    preds = test_df.mapInPandas(predict, "prediction double")
    n = preds.count()
    assert n == test_df.count()
    # pyfunc-style whole-frame UDF via the tracking module
    with mlflow.start_run() as run:
        mlflow.spark.log_model(model, "model")
    udf_model = mlflow.pyfunc.spark_udf(spark,
                                        f"runs:/{run.info.run_id}/model")
    out = test_df.withColumn("prediction",
                             udf_model(*test_df.columns)).toPandas()
    assert np.isfinite(out["prediction"]).all()


def test_ml13_pandas_function_api(spark, clean_dir, tmp_path):
    """Per-group model training through applyInPandas (`ML 13:119-161`)."""
    from sml_tpu import tracking as mlflow
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    mlflow.set_experiment("ml13")
    df = spark.read.format("delta").load(clean_dir)

    def train_model(pdf):
        from sklearn.linear_model import LinearRegression as SkLR
        g = pdf.dropna(subset=["bedrooms", "accommodates", "price"])
        m = SkLR().fit(g[["bedrooms", "accommodates"]], g["price"])
        mse = float(np.mean(
            (m.predict(g[["bedrooms", "accommodates"]]) - g["price"]) ** 2))
        return pd.DataFrame({"room_type": [g["room_type"].iloc[0]],
                             "n_used": [len(g)], "mse": [mse]})

    out = df.groupby("room_type").applyInPandas(
        train_model, "room_type string, n_used bigint, mse double").toPandas()
    assert len(out) == df.toPandas()["room_type"].nunique()
    assert np.isfinite(out["mse"]).all()


# -------------------------------------------------------------------- ML 14
def test_ml14_koalas(spark, clean_dir):
    import matplotlib
    matplotlib.use("Agg")
    from sml_tpu import pandas_api as ks
    df = spark.read.format("delta").load(clean_dir)
    kdf = ks.DataFrame(df)
    vc = kdf["room_type"].value_counts()
    assert vc.sum() == df.count()
    ks.options.plotting.backend = "matplotlib"
    assert kdf.filter(items=["bedrooms", "price"]) \
        .plot.hist(x="bedrooms", y="price", bins=20) is not None
    distinct = ks.sql("select distinct(room_type) from {kdf}")
    assert len(distinct.to_pandas()) == df.toPandas()["room_type"].nunique()


# ------------------------------------------------------------------ electives
def test_mle00_streaming_inference(spark, clean_dir, tmp_path):
    """Micro-batch scoring of a file stream (`MLE 00`)."""
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    df = spark.read.format("delta").load(clean_dir)
    vec = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                          outputCol="features")
    model = LinearRegression(labelCol="price").fit(vec.transform(df))
    src = tmp_path / "stream-src"
    src.mkdir()
    pdf = df.toPandas()
    for i in range(3):
        pdf.iloc[i * 100:(i + 1) * 100].to_parquet(src / f"part-{i}.parquet")
    stream = (spark.readStream.format("parquet")
              .option("maxFilesPerTrigger", 1)
              .schema(df.schema).load(str(src)))
    scored = model.transform(vec.transform(stream))
    q = (scored.writeStream.format("memory").queryName("mle00_preds")
         .option("checkpointLocation", str(tmp_path / "ckpt"))
         .trigger(processingTime="0 seconds").start())
    q.processAllAvailable()
    out = spark.sql("SELECT count(*) AS n FROM mle00_preds").toPandas()
    q.stop()
    assert int(out["n"].iloc[0]) == 300


def test_mle01_als_collaborative_filtering(spark):
    """ALS on MovieLens-shaped ratings + RMSE evaluation (`MLE 01`)."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.recommendation import ALS
    ratings = make_movielens_dataset(n_users=300, n_items=120,
                                     n_ratings=8000, seed=42)
    df = spark.createDataFrame(ratings)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    als = ALS(userCol="userId", itemCol="movieId", ratingCol="rating",
              rank=8, maxIter=5, regParam=0.1, seed=42,
              coldStartStrategy="drop")
    model = als.fit(train_df)
    preds = model.transform(test_df)
    rmse = RegressionEvaluator(labelCol="rating").evaluate(preds)
    assert 0.5 < rmse < 2.0  # sane for 1-5 star synthetic ratings
    recs = model.recommendForAllUsers(3).toPandas()
    assert len(recs) > 0


def test_mle02_kmeans(spark):
    """KMeans on the iris-like flow with cluster quality (`MLE 02`)."""
    from sklearn.datasets import make_blobs
    from sml_tpu.ml.clustering import KMeans
    from sml_tpu.ml.evaluation import ClusteringEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    X, _ = make_blobs(n_samples=600, centers=3, cluster_std=1.0,
                      random_state=42)
    pdf = pd.DataFrame(X, columns=["f0", "f1"])
    df = spark.createDataFrame(pdf)
    fdf = VectorAssembler(inputCols=["f0", "f1"],
                          outputCol="features").transform(df)
    model = KMeans(k=3, seed=42, maxIter=20).fit(fdf)
    preds = model.transform(fdf)
    sil = ClusteringEvaluator().evaluate(preds)
    assert sil > 0.5  # well-separated blobs
    assert len(model.clusterCenters()) == 3


def test_mle03_logistic_regression(spark, clean_dir):
    """Binary classification with AUROC (`MLE 03`)."""
    from sml_tpu.ml.classification import LogisticRegression
    from sml_tpu.ml.evaluation import BinaryClassificationEvaluator
    from sml_tpu.ml.feature import StringIndexer, VectorAssembler
    df = spark.read.format("delta").load(clean_dir)
    df = df.withColumn("label",
                       F.when(F.col("price") >= 150, 1.0).otherwise(0.0))
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    stages_df = StringIndexer(inputCols=["room_type"],
                              outputCols=["room_typeIndex"],
                              handleInvalid="skip")
    tr = stages_df.fit(train_df).transform(train_df)
    te = stages_df.fit(train_df).transform(test_df)
    vec = VectorAssembler(inputCols=["room_typeIndex", "bedrooms",
                                     "accommodates"], outputCol="features")
    model = LogisticRegression(labelCol="label").fit(vec.transform(tr))
    preds = model.transform(vec.transform(te))
    auc = BinaryClassificationEvaluator(labelCol="label").evaluate(preds)
    assert auc > 0.6


def test_mle04_time_series(spark):
    """ADF test → ARIMA(1,2,1) → Prophet-style forecast (`MLE 04`)."""
    from sml_tpu.timeseries import ARIMA, Prophet, adfuller
    t = np.arange(160, dtype=float)
    rng = np.random.default_rng(42)
    y = 0.02 * t * t + 1.5 * t + 20 + rng.normal(scale=1.0, size=len(t))
    stat, pvalue = adfuller(y)[:2]
    assert pvalue > 0.05  # trending series: non-stationary, as taught
    res = ARIMA(y, order=(1, 2, 1)).fit()
    assert np.isfinite(res.aic)
    fc = res.forecast(10)
    assert np.isfinite(fc).all() and fc[-1] > y[-1]
    ds = pd.date_range("2020-01-01", periods=len(t), freq="D")
    m = Prophet()
    m.fit(pd.DataFrame({"ds": ds, "y": y}))
    future = m.make_future_dataframe(periods=10)
    fcst = m.predict(future)
    assert {"ds", "yhat"} <= set(fcst.columns)
    assert len(fcst) == len(t) + 10


# ---------------------------------------------------------------------- labs
def test_ml01L_eda_baseline_predictors(spark, clean_dir):
    """Lab ML 01L (`Labs/ML 01L:44-168`): log-price view, group counts,
    approxQuantile median, then the avg/median BASELINE predictors whose
    test RMSE the real models must beat — the lab's stated outcome is that
    the mean baseline wins under RMSE (squared loss favors the mean)."""
    from sml_tpu.ml.evaluation import RegressionEvaluator

    airbnb_df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = airbnb_df.randomSplit([.8, .2], seed=42)

    # log(price) histogram data: positive prices → finite logs
    logs = train_df.select(F.log("price")).toPandas()
    assert np.isfinite(logs.to_numpy()).all()

    # neighbourhood group counts, descending (`display` cells)
    counts = (train_df.groupBy("neighbourhood_cleansed").count()
              .orderBy(F.col("count").desc()).toPandas())
    assert counts["count"].is_monotonic_decreasing

    avg_price = train_df.select(F.avg("price")).first()[0]
    median_price = train_df.approxQuantile(
        "price", probabilities=[0.5], relativeError=0.01)[0]
    assert median_price < avg_price  # skewed price distribution

    pred_df = (test_df
               .withColumn("avgPrediction", F.lit(avg_price))
               .withColumn("medianPrediction", F.lit(median_price)))
    rmse_avg = RegressionEvaluator(
        predictionCol="avgPrediction", labelCol="price",
        metricName="rmse").evaluate(pred_df)
    rmse_median = RegressionEvaluator(
        predictionCol="medianPrediction", labelCol="price",
        metricName="rmse").evaluate(pred_df)
    assert 0 < rmse_avg < rmse_median  # the lab's punchline


def test_ml02L_lr_coefficient_readout(spark, clean_dir):
    """Lab ML 02L (`Labs/ML 02L:35-62`): the 5-feature assembler + LR fit,
    rmse/r2, and the coefficient readout — beats the ML 01L baselines."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression

    airbnb_df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = airbnb_df.randomSplit([.8, .2], seed=42)
    feats = ["bedrooms", "bathrooms", "bathrooms_na", "minimum_nights",
             "number_of_reviews"]
    vec_assembler = VectorAssembler(inputCols=feats, outputCol="features")
    lr_model = LinearRegression(featuresCol="features", labelCol="price") \
        .fit(vec_assembler.transform(train_df))
    pred_df = lr_model.transform(vec_assembler.transform(test_df))
    ev = RegressionEvaluator(predictionCol="prediction", labelCol="price",
                             metricName="rmse")
    rmse = ev.evaluate(pred_df)
    r2 = ev.setMetricName("r2").evaluate(pred_df)
    assert 0 < r2 < 1

    # coefficient readout: one per feature + finite intercept
    coefs = dict(zip(feats, lr_model.coefficients))
    assert len(coefs) == 5 and all(np.isfinite(v) for v in coefs.values())
    assert np.isfinite(lr_model.intercept)
    assert coefs["bedrooms"] > 0  # more bedrooms → higher price

    # beats the mean baseline from ML 01L
    avg_price = train_df.select(F.avg("price")).first()[0]
    base = RegressionEvaluator(
        predictionCol="avgPrediction", labelCol="price",
        metricName="rmse").evaluate(
            test_df.withColumn("avgPrediction", F.lit(avg_price)))
    assert rmse < base


def test_ml03L_rformula_log_price(spark, clean_dir):
    """The lab's exact RFormula flow: `log_price ~ . - price` with skip
    handling, predict in log space, exp back (`Labs/ML 03L:81-102`)."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import RFormula
    from sml_tpu.ml.regression import LinearRegression
    df = spark.read.format("delta").load(clean_dir) \
        .select("room_type", "bedrooms", "accommodates", "price")
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    log_train_df = train_df.withColumn("log_price", F.log(F.col("price")))
    log_test_df = test_df.withColumn("log_price", F.log(F.col("price")))
    r_formula = RFormula(formula="log_price ~ . - price",
                         featuresCol="features", labelCol="log_price",
                         handleInvalid="skip")
    lr = LinearRegression(labelCol="log_price", predictionCol="log_pred")
    pipeline_model = Pipeline(stages=[r_formula, lr]).fit(log_train_df)
    pred_df = pipeline_model.transform(log_test_df)
    exp_df = pred_df.withColumn("prediction", F.exp(F.col("log_pred")))
    rmse = RegressionEvaluator(labelCol="price").evaluate(exp_df)
    assert 0 < rmse < 200
    # the excluded column must NOT be a feature: room_type one-hots to
    # (categories - 1) slots under dropLast, plus bedrooms + accommodates;
    # price appearing as a feature would add one more slot
    pdf = exp_df.toPandas()
    width = pdf["features"].iloc[0].size
    n_room_types = df.toPandas()["room_type"].nunique()
    assert width == (n_room_types - 1) + 2


def test_ml07L_cv_inside_pipeline(spark, clean_dir):
    """The lab puts the CrossValidator INSIDE the pipeline
    (`Labs/ML 07L:130-150`) — an estimator mid-chain must fit and its
    model must transform."""
    from sml_tpu.ml import Pipeline
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import StringIndexer, VectorAssembler
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder
    df = spark.read.format("delta").load(clean_dir)
    train_df, test_df = df.randomSplit([.8, .2], seed=42)
    string_indexer = StringIndexer(inputCols=["room_type"],
                                   outputCols=["room_typeIndex"],
                                   handleInvalid="skip")
    vec_assembler = VectorAssembler(
        inputCols=["room_typeIndex", "bedrooms", "accommodates"],
        outputCol="features")
    rf = RandomForestRegressor(labelCol="price", seed=42, maxBins=40)
    param_grid = (ParamGridBuilder()
                  .addGrid(rf.getParam("maxDepth"), [2, 5])
                  .addGrid(rf.getParam("numTrees"), [5, 10]).build())
    evaluator = RegressionEvaluator(labelCol="price")
    cv = CrossValidator(estimator=rf, evaluator=evaluator,
                        estimatorParamMaps=param_grid, numFolds=3,
                        parallelism=4, seed=42)
    pipeline = Pipeline(stages=[string_indexer, vec_assembler, cv])
    pipeline_model = pipeline.fit(train_df)
    pred_df = pipeline_model.transform(test_df)
    rmse = evaluator.evaluate(pred_df)
    assert 0 < rmse < 200


def test_ml08L_hyperopt_over_sklearn(spark, clean_dir):
    """The lab's shape: fmin over a SINGLE-NODE sklearn objective
    (`Labs/ML 08L:97-126`) — the payload is arbitrary Python."""
    from sklearn.ensemble import RandomForestRegressor as SkRF
    from sklearn.model_selection import cross_val_score, train_test_split
    from sml_tpu.tune import STATUS_OK, Trials, fmin, hp, tpe
    pdf = spark.read.format("delta").load(clean_dir).toPandas()
    X = pdf[["bedrooms", "accommodates"]].to_numpy()
    y = pdf["price"].to_numpy()
    X_train, _, y_train, _ = train_test_split(X, y, random_state=42)

    def objective(params):
        model = SkRF(n_estimators=int(params["n_estimators"]),
                     max_depth=int(params["max_depth"]), random_state=42)
        score = cross_val_score(model, X_train[:2000], y_train[:2000],
                                cv=3, scoring="r2").mean()
        return {"loss": -score, "status": STATUS_OK}

    space = {"n_estimators": hp.quniform("n_estimators", 5, 20, 5),
             "max_depth": hp.quniform("max_depth", 2, 6, 1)}
    trials = Trials()
    best = fmin(objective, space, algo=tpe, max_evals=4, trials=trials,
                rstate=np.random.RandomState(42))
    assert len(trials.trials) == 4 and "max_depth" in best


def test_ml12L_sklearn_flavor_spark_udf(spark, clean_dir, tmp_path):
    """The lab logs a single-node sklearn model and scores it at scale
    through the pyfunc spark_udf (`Labs/ML 12L`)."""
    from sklearn.ensemble import RandomForestRegressor as SkRF
    from sml_tpu import tracking as mlflow
    mlflow.set_tracking_uri(str(tmp_path / "mlruns"))
    pdf = spark.read.format("delta").load(clean_dir).toPandas()
    Xcols = ["bedrooms", "accommodates"]
    with mlflow.start_run() as run:
        skm = SkRF(n_estimators=10, max_depth=4, random_state=42)
        skm.fit(pdf[Xcols], pdf["price"])
        mlflow.sklearn.log_model(skm, "sk-model")
    predict = mlflow.pyfunc.spark_udf(spark,
                                      f"runs:/{run.info.run_id}/sk-model")
    df = spark.read.format("delta").load(clean_dir)
    out = df.withColumn("prediction", predict(*Xcols)).toPandas()
    assert np.isfinite(out["prediction"]).all()
    ref = skm.predict(pdf[Xcols])
    np.testing.assert_allclose(np.sort(out["prediction"].to_numpy()),
                               np.sort(ref), rtol=1e-6)
