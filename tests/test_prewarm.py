"""Program-prewarm manifest (sml_tpu/parallel/prewarm.py): recording,
concurrent replay, golden parity, and mesh-signature gating.

The contract: a process that replays a warm manifest first-dispatches
every recorded program BEFORE first use (prewarm.* counters + event
ordering), subsequent same-shape fits add ZERO program-cache misses,
and model outputs are bit-identical to an unprewarmed process.
"""

import json
import os

import numpy as np
import pandas as pd
import pytest

from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.utils.profiler import PROFILER


@pytest.fixture()
def prewarm_env(tmp_path):
    """Point the compile cache (and therefore the manifest) at a fresh
    directory, with the profiler on for counter assertions."""
    prev_dir = GLOBAL_CONF.get("sml.compile.cacheDir")
    prev_prof = GLOBAL_CONF.get("sml.profiler.enabled")
    GLOBAL_CONF.set("sml.compile.cacheDir", str(tmp_path))
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    yield str(tmp_path)
    GLOBAL_CONF.set("sml.compile.cacheDir", prev_dir or "")
    GLOBAL_CONF.set("sml.profiler.enabled", prev_prof)


@pytest.fixture()
def reg_frames(spark):
    rng = np.random.default_rng(0)
    n = 4000
    pdf = pd.DataFrame({f"f{i}": rng.normal(size=n) for i in range(4)})
    pdf["label"] = pdf["f0"] * 2 + rng.normal(0, 0.1, n)
    from sml_tpu.ml.feature import VectorAssembler
    fdf = VectorAssembler(inputCols=[f"f{i}" for i in range(4)],
                          outputCol="features") \
        .transform(spark.createDataFrame(pdf))
    fdf.cache()
    X = pdf[[f"f{i}" for i in range(4)]].to_numpy(np.float32)
    return fdf, X


def _clear_program_caches():
    """Simulate a cold process: drop every per-process program cache the
    prewarm replay is supposed to repopulate."""
    from sml_tpu.ml import _staging, inference, tree_impl
    tree_impl._ensemble_cache.clear()
    tree_impl._folds_cache.clear()
    tree_impl._trials_cache.clear()
    tree_impl._chunk_cache.clear()
    _staging._compiled_cache.clear()
    inference._forest_programs.clear()


def _delta(c0, c1, name):
    return c1.get(name, 0.0) - c0.get(name, 0.0)


def test_prewarm_records_replays_and_golden_parity(prewarm_env, reg_frames):
    from sml_tpu import obs
    from sml_tpu.ml import DeviceScorer
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.parallel import prewarm

    fdf, X = reg_frames
    rf = RandomForestRegressor(labelCol="label", numTrees=4, maxDepth=3,
                               seed=3)
    pred_before = DeviceScorer(rf.fit(fdf)).score_block(X)

    mpath = os.path.join(prewarm_env, "prewarm_manifest.json")
    assert os.path.exists(mpath)
    with open(mpath) as f:
        man = json.load(f)
    kinds = {e["kind"] for e in man["entries"].values()}
    assert "tree_ensemble" in kinds          # the fit program
    assert "data_parallel" in kinds          # the scorer forward

    _clear_program_caches()
    GLOBAL_CONF.set("sml.obs.enabled", True)
    try:
        obs.reset()
        c0 = PROFILER.counters()
        stats = prewarm.prewarm(workers=2)
        c1 = PROFILER.counters()
        assert stats["programs"] >= 2
        assert stats["failed"] == 0
        assert stats["replayed"] == stats["programs"]
        assert _delta(c0, c1, "prewarm.replayed") == stats["programs"]
        assert _delta(c0, c1, "prewarm.failed") == 0

        # warm caches: the SAME fit + score adds zero program-cache
        # misses — prewarm paid every build/first-dispatch up front...
        c0 = PROFILER.counters()
        pred_after = DeviceScorer(rf.fit(fdf)).score_block(X)
        c1 = PROFILER.counters()
        assert _delta(c0, c1, "compile.programs") == 0
        # ...and all prewarm activity strictly precedes first use: every
        # prewarm.* event sits before any post-prewarm program span
        events = obs.RECORDER.events()
        names = [e.name for e in events]
        assert "prewarm.start" in names and "prewarm.done" in names
        last_prewarm = max(i for i, n in enumerate(names)
                           if n.startswith("prewarm."))
        first_program = min((i for i, e in enumerate(events)
                             if e.kind == "span"
                             and e.name.startswith("program.")
                             and i > names.index("prewarm.done")),
                            default=len(events))
        assert last_prewarm < first_program or \
            names[last_prewarm] == "prewarm.done"
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", False)
    # golden parity: a prewarmed process produces identical outputs
    np.testing.assert_array_equal(pred_before, pred_after)


def test_prewarm_covers_grid_fused_trials(prewarm_env, reg_frames):
    """A grid-fused CV records its trial-batched program; a cold process
    replays it and the next CV fit compiles nothing."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder
    from sml_tpu.parallel import prewarm

    fdf, _ = reg_frames
    rf = RandomForestRegressor(labelCol="label", maxBins=8, seed=7)
    grid = (ParamGridBuilder()
            .addGrid(rf.getParam("maxDepth"), [2, 3]).build())
    cv = CrossValidator(estimator=rf, estimatorParamMaps=grid,
                        evaluator=RegressionEvaluator(labelCol="label"),
                        numFolds=2, parallelism=1, seed=11)
    GLOBAL_CONF.set("sml.cv.batchFolds", True)
    try:
        metrics_before = cv.fit(fdf).avgMetrics
        with open(os.path.join(prewarm_env, "prewarm_manifest.json")) as f:
            kinds = {e["kind"] for e in json.load(f)["entries"].values()}
        assert "tree_trials" in kinds
        _clear_program_caches()
        stats = prewarm.prewarm(workers=4)
        assert stats["failed"] == 0 and stats["replayed"] >= 2
        c0 = PROFILER.counters()
        metrics_after = cv.fit(fdf).avgMetrics
        c1 = PROFILER.counters()
        assert _delta(c0, c1, "compile.programs") == 0
    finally:
        GLOBAL_CONF.unset("sml.cv.batchFolds")
    np.testing.assert_array_equal(metrics_before, metrics_after)


def test_prewarm_skips_foreign_mesh_entries(prewarm_env, reg_frames):
    """Entries recorded under a different mesh signature (data-axis width
    or platform) must be skipped, not replayed onto the wrong mesh."""
    from sml_tpu.ml.regression import DecisionTreeRegressor
    from sml_tpu.parallel import prewarm

    fdf, _ = reg_frames
    DecisionTreeRegressor(labelCol="label", maxDepth=2, seed=1).fit(fdf)
    mpath = os.path.join(prewarm_env, "prewarm_manifest.json")
    with open(mpath) as f:
        man = json.load(f)
    assert man["entries"]
    for e in man["entries"].values():
        e["mesh"] = [64, "tpu"]  # nothing local matches this
    with open(mpath, "w") as f:
        json.dump(man, f)
    # drop the in-memory manifest cache so the doctored file is re-read
    prewarm._state["entries"] = None
    stats = prewarm.prewarm()
    assert stats["programs"] == 0
    assert stats["skipped"] == len(man["entries"])


def test_maybe_prewarm_is_opt_in_and_guarded_per_manifest_mesh(
        prewarm_env, monkeypatch, tmp_path):
    """The replay guard is keyed per (manifest, mesh) — NOT once per
    process: replica 2..N under the same warm caches skip (counted
    prewarm.replica_skip), while a re-pointed compile-cache dir is a
    genuinely cold world that warms again."""
    from sml_tpu.parallel import prewarm
    from sml_tpu.utils.profiler import PROFILER

    calls = []
    monkeypatch.setattr(prewarm, "prewarm", lambda **kw: calls.append(1))
    monkeypatch.setattr(prewarm, "_ran", {})
    assert prewarm.maybe_prewarm(block=True) is None  # conf off: no-op
    GLOBAL_CONF.set("sml.prewarm.enabled", True)
    try:
        prewarm.maybe_prewarm(block=True)
        assert calls == [1]
        # the claim happens in maybe_prewarm itself (not in the replay
        # thread), so back-to-back replica constructions cannot both
        # launch a replay; the shared-warm-cache skip is COUNTED
        assert prewarm._ran.get(prewarm._guard_key()) is True
        skip0 = PROFILER.counters().get("prewarm.replica_skip", 0.0)
        assert prewarm.maybe_prewarm(block=True) is None
        assert PROFILER.counters().get("prewarm.replica_skip", 0.0) \
            == skip0 + 1
        assert calls == [1]
        # a re-pointed compile cache = a different manifest = cold
        # caches for this key: the guard must NOT carry over
        other = tmp_path / "other-cache"
        GLOBAL_CONF.set("sml.compile.cacheDir", str(other))
        prewarm.maybe_prewarm(block=True)
        assert calls == [1, 1]
    finally:
        GLOBAL_CONF.unset("sml.prewarm.enabled")
        GLOBAL_CONF.set("sml.compile.cacheDir", prewarm_env)
    assert calls == [1, 1]
