"""The pyspark/mlflow/hyperopt/databricks import shims: course notebook
code runs UNCHANGED against the TPU framework (sml_tpu/compat.py).

The import lines below are the reference's actual import census (every
`from pyspark…`/`databricks…`/`sparkdl…`/`hyperopt…` statement found in
the course tree), followed by a mini ML 02-shaped flow written exactly as
the course writes it.
"""

import numpy as np
import pandas as pd

from sml_tpu.compat import install_shims

install_shims()


def test_course_import_census():
    # pyspark.sql
    from pyspark.sql.functions import col, lit, log, exp, when, translate  # noqa
    from pyspark.sql.functions import (monotonically_increasing_id, rand,  # noqa
                                       pandas_udf)
    from pyspark.sql.types import (DoubleType, IntegerType, StringType,  # noqa
                                   StructType, Row)
    # pyspark.ml
    from pyspark.ml import Pipeline, PipelineModel  # noqa
    from pyspark.ml.feature import (Imputer, OneHotEncoder, RFormula,  # noqa
                                    StringIndexer, VectorAssembler)
    from pyspark.ml.regression import (DecisionTreeRegressor,  # noqa
                                       LinearRegression,
                                       RandomForestRegressor)
    from pyspark.ml.classification import LogisticRegression  # noqa
    from pyspark.ml.clustering import KMeans  # noqa
    from pyspark.ml.recommendation import ALS  # noqa
    from pyspark.ml.evaluation import (BinaryClassificationEvaluator,  # noqa
                                       MulticlassClassificationEvaluator,
                                       RegressionEvaluator)
    from pyspark.ml.tuning import CrossValidator, ParamGridBuilder  # noqa
    from pyspark.ml.linalg import Vectors  # noqa
    # mlflow
    import mlflow  # noqa
    import mlflow.spark  # noqa
    import mlflow.sklearn  # noqa
    import mlflow.pyfunc  # noqa
    from mlflow.tracking import MlflowClient  # noqa
    from mlflow.tracking.client import MlflowClient as MC2  # noqa
    from mlflow.models.signature import infer_signature  # noqa
    # hyperopt
    from hyperopt import (SparkTrials, STATUS_OK, Trials, fmin, hp,  # noqa
                          tpe)
    # sparkdl / databricks
    from sparkdl.xgboost import XgboostRegressor  # noqa
    from databricks import automl, feature_store  # noqa
    from databricks.feature_store import FeatureLookup, FeatureStoreClient  # noqa
    from databricks.feature_store import feature_table  # noqa
    import databricks.koalas as ks  # noqa
    assert hasattr(ks, "DataFrame")


def test_course_code_runs_verbatim(spark, airbnb_pdf):
    """An ML 02/03-shaped cell sequence, written the course's way."""
    from pyspark.ml import Pipeline
    from pyspark.ml.feature import StringIndexer, VectorAssembler
    from pyspark.ml.regression import LinearRegression
    from pyspark.ml.evaluation import RegressionEvaluator
    from pyspark.sql.functions import col

    airbnb_df = spark.createDataFrame(airbnb_pdf)
    train_df, test_df = airbnb_df.withColumn(
        "price", col("price").cast("double")).randomSplit([.8, .2], seed=42)

    categorical_cols = ["room_type"]
    index_output_cols = [x + "Index" for x in categorical_cols]
    string_indexer = StringIndexer(inputCols=categorical_cols,
                                   outputCols=index_output_cols,
                                   handleInvalid="skip")
    numeric_cols = ["bedrooms", "accommodates"]
    assembler_inputs = index_output_cols + numeric_cols
    vec_assembler = VectorAssembler(inputCols=assembler_inputs,
                                    outputCol="features")
    lr = LinearRegression(labelCol="price", featuresCol="features")
    stages = [string_indexer, vec_assembler, lr]
    pipeline = Pipeline(stages=stages)
    pipeline_model = pipeline.fit(train_df)
    pred_df = pipeline_model.transform(test_df)
    regression_evaluator = RegressionEvaluator(predictionCol="prediction",
                                               labelCol="price",
                                               metricName="rmse")
    rmse = regression_evaluator.evaluate(pred_df)
    r2 = regression_evaluator.setMetricName("r2").evaluate(pred_df)
    assert np.isfinite(rmse) and rmse > 0
    assert -1 < r2 <= 1


def test_spark_session_builder_shim():
    from pyspark.sql import SparkSession
    s = SparkSession.builder.appName("compat").getOrCreate()
    df = s.createDataFrame(pd.DataFrame({"x": [1, 2, 3]}))
    assert df.count() == 3
