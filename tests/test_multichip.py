"""Multi-chip execution mode (ISSUE 6): the ICI-sharded histogram engine
proven on the simulated 8-device mesh.

Contracts:

- DEVICE-COUNT INVARIANCE: DT/RF/xgboost fits and CV avgMetrics on an
  8-device mesh match a 1-device mesh (sampling draws are
  mesh-layout-invariant — `tree_impl._sliced_draw`; remaining drift is
  float reduction order, bounded by tolerance), and `tree.fit_dispatch`
  counts are identical (the fused-dispatch contract of
  tests/test_dispatch_economics.py holds at every width).
- SHARDED BIN RESIDENCY: the quantized bin matrix staged by
  `stage_sharded` genuinely spans all 8 devices, one row block apiece.
- OBSERVABLE ALLREDUCE VOLUME: `collective.psum_bytes` counts the
  histogram payload per split round, halves under histogram
  subtraction, and renders on the trace exporter's counter tracks.
- CROSS-CHIP TRIAL PARALLELISM: `sml.cv.trialAxisDevices` shards fused
  (grid x fold) elements over a second mesh axis with unchanged metrics.
- The 8-simulated-device dryrun subprocess exits 0 (the MULTICHIP_r01
  crash class can never regress silently), and a foreign-mesh prewarm
  manifest is skipped, not replayed onto the 8-device mesh.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.utils.profiler import PROFILER

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


@pytest.fixture()
def fused_debug(monkeypatch):
    monkeypatch.setenv("SML_FUSED_DEBUG", "1")


@pytest.fixture()
def profiled():
    prev = GLOBAL_CONF.get("sml.profiler.enabled")
    GLOBAL_CONF.set("sml.profiler.enabled", True)
    yield PROFILER
    GLOBAL_CONF.set("sml.profiler.enabled", prev)


@pytest.fixture()
def xy():
    rng = np.random.default_rng(11)
    n = 4096
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] * 3 - X[:, 1] ** 2 + 0.5 * X[:, 2]
         + rng.normal(0, 0.2, n)).astype(np.float32)
    return X, y


def _frame(spark, X, y, label="label"):
    from sml_tpu.ml.feature import VectorAssembler
    pdf = pd.DataFrame({f"f{i}": X[:, i] for i in range(X.shape[1])})
    pdf[label] = y
    fdf = VectorAssembler(inputCols=[f"f{i}" for i in range(X.shape[1])],
                          outputCol="features") \
        .transform(spark.createDataFrame(pdf))
    fdf.cache()
    return fdf


def _mesh(width):
    from sml_tpu.parallel import mesh as meshlib
    return meshlib.use_mesh(meshlib.build_mesh(width))


# --------------------------------------------------- sharded bin residency
def test_bin_matrix_shards_rows_across_all_devices(xy):
    """The quantized bin matrix staged for a fit is genuinely distributed:
    8 addressable shards, each holding exactly 1/8 of the padded rows —
    per-device partial histograms + psum are real, not a replicated
    array pretending to be sharded."""
    import jax

    from sml_tpu.ml import tree_impl
    from sml_tpu.parallel import mesh as meshlib

    X, y = xy
    assert len(jax.devices()) >= 8
    with _mesh(8):
        staged = tree_impl.stage_tree_data(X, y, max_bins=16)
        arr = staged.binned_dev
        assert arr.dtype == np.uint8  # compact quantized residency
        assert len(arr.sharding.device_set) == 8
        shards = arr.addressable_shards
        assert len(shards) == 8
        n_pad = arr.shape[0]
        assert n_pad % 8 == 0
        assert all(s.data.shape[0] == n_pad // 8 for s in shards)
        # aligned per-row operands ride the same row split
        assert len(staged.mask_dev.sharding.device_set) == 8
        assert meshlib.mesh_device_count() == 8


# ------------------------------------------------ device-count invariance
def _fit_predict(spark, X, y, estimator_factory, width, log_label=False):
    from sml_tpu.ml.evaluation import RegressionEvaluator
    yy = np.log(y - y.min() + 1.0) if log_label else y
    fdf = _frame(spark, X, yy)
    with _mesh(width):
        model = estimator_factory().fit(fdf)
        pred = model.transform(fdf).toPandas()["prediction"].to_numpy()
        rmse = RegressionEvaluator(labelCol="label").evaluate(
            model.transform(fdf))
    return pred, rmse


@pytest.mark.parametrize("kind", ["dt", "rf", "xgb"])
def test_fit_goldens_8dev_vs_1dev(spark, xy, kind):
    """The same estimator fit on 8 devices and on 1 device produces the
    same model (predictions + rmse within float reduction-order
    tolerance). Before r6, RF/boosting sampling folded the shard index
    into its key, so the fitted forest depended on the mesh LAYOUT."""
    X, y = xy

    def factory():
        from sml_tpu.ml.regression import (DecisionTreeRegressor,
                                           RandomForestRegressor)
        from sml_tpu.xgboost import XgboostRegressor
        if kind == "dt":
            return DecisionTreeRegressor(labelCol="label", maxDepth=5,
                                         maxBins=16)
        if kind == "rf":
            return RandomForestRegressor(labelCol="label", maxDepth=4,
                                         numTrees=8, maxBins=16,
                                         subsamplingRate=0.9, seed=7)
        return XgboostRegressor(n_estimators=8, max_depth=4, max_bins=16,
                                learning_rate=0.3, subsample=0.8,
                                random_state=5)

    p8, rmse8 = _fit_predict(spark, X, y, factory, 8)
    p1, rmse1 = _fit_predict(spark, X, y, factory, 1)
    np.testing.assert_allclose(p8, p1, rtol=1e-4, atol=1e-4)
    assert abs(rmse8 - rmse1) < 1e-4 * max(abs(rmse1), 1.0)


def test_cv_avgmetrics_and_dispatch_parity_8dev_vs_1dev(spark, xy,
                                                        profiled,
                                                        fused_debug):
    """Grid-fused CV on the 8-device mesh: avgMetrics match the 1-device
    run AND both widths spend the same `tree.fit_dispatch` budget —
    ceil(G*k/maxFusedTrials) fused dispatches + the winner refit (the
    test_dispatch_economics contract, now asserted per mesh width)."""
    import math

    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder

    X, y = xy
    fdf = _frame(spark, X, y)
    rf = RandomForestRegressor(labelCol="label", maxBins=16, seed=7)
    grid = (ParamGridBuilder()
            .addGrid(rf.getParam("maxDepth"), [2, 4])
            .addGrid(rf.getParam("numTrees"), [3, 6]).build())
    cv = CrossValidator(estimator=rf, estimatorParamMaps=grid,
                        evaluator=RegressionEvaluator(labelCol="label"),
                        numFolds=3, parallelism=1, seed=13)
    G, k, fuse = len(grid), 3, 6
    GLOBAL_CONF.set("sml.cv.batchFolds", True)
    GLOBAL_CONF.set("sml.cv.maxFusedTrials", fuse)
    try:
        counts, metrics = {}, {}
        for width in (8, 1):
            with _mesh(width):
                c0 = PROFILER.counters()
                metrics[width] = cv.fit(fdf).avgMetrics
                c1 = PROFILER.counters()
            counts[width] = c1.get("tree.fit_dispatch", 0.0) \
                - c0.get("tree.fit_dispatch", 0.0)
    finally:
        GLOBAL_CONF.unset("sml.cv.maxFusedTrials")
        GLOBAL_CONF.unset("sml.cv.batchFolds")
    np.testing.assert_allclose(metrics[8], metrics[1],
                               rtol=1e-4, atol=1e-4)
    assert counts[8] == counts[1]
    assert counts[8] <= math.ceil(G * k / fuse) + 1


# ------------------------------------------- cross-chip trial parallelism
def test_trial_axis_sharding_parity_and_widths(spark, xy, fused_debug):
    """`sml.cv.trialAxisDevices` moves fused elements onto a second mesh
    axis: metrics match the rows-only layout, and the auto policy picks
    a real width on the 8-device mesh for small-row trials."""
    from sml_tpu.ml import tree_impl
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder

    X, y = xy
    fdf = _frame(spark, X, y)
    rf = RandomForestRegressor(labelCol="label", maxBins=16, seed=3)
    grid = (ParamGridBuilder()
            .addGrid(rf.getParam("maxDepth"), [2, 3])
            .addGrid(rf.getParam("numTrees"), [2, 4]).build())
    cv = CrossValidator(estimator=rf, estimatorParamMaps=grid,
                        evaluator=RegressionEvaluator(labelCol="label"),
                        numFolds=2, parallelism=1, seed=5)
    out = {}
    GLOBAL_CONF.set("sml.cv.batchFolds", True)
    try:
        with _mesh(8):
            for knob in (1, 8, 0):
                GLOBAL_CONF.set("sml.cv.trialAxisDevices", knob)
                out[knob] = cv.fit(fdf).avgMetrics
    finally:
        GLOBAL_CONF.unset("sml.cv.trialAxisDevices")
        GLOBAL_CONF.unset("sml.cv.batchFolds")
    np.testing.assert_allclose(out[8], out[1], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out[0], out[1], rtol=1e-4, atol=1e-4)
    # the auto policy: 8 fused elements x small rows -> full trial width;
    # a giant per-trial row count keeps the rows-only layout; auto never
    # pads (E=5 has no admissible divisor) but an EXPLICIT width is
    # honored by padding the element axis
    with _mesh(8):
        assert tree_impl._trial_axis_width(8, 4096) == 8
        assert tree_impl._trial_axis_width(12, 4096) == 4  # zero padding
        assert tree_impl._trial_axis_width(8, 1 << 20) == 1
        assert tree_impl._trial_axis_width(5, 4096) == 1
        GLOBAL_CONF.set("sml.cv.trialAxisDevices", 8)
        try:
            assert tree_impl._trial_axis_width(5, 4096) == 8  # pads 5->8
        finally:
            GLOBAL_CONF.unset("sml.cv.trialAxisDevices")
    with _mesh(1):
        assert tree_impl._trial_axis_width(8, 4096) == 1


def test_explicit_trial_width_pads_elements_with_parity(xy):
    """An explicit `sml.cv.trialAxisDevices` that does not divide the
    element count pads the trial axis (repeating element 0) and still
    returns exactly E correct results — the knob is honored, never
    silently ignored."""
    import jax

    from sml_tpu.ml import tree_impl

    X, y = xy
    E, nr = 5, 1024
    rng = np.random.default_rng(2)
    from sml_tpu.parallel import mesh as meshlib
    with _mesh(8):
        n_pad = meshlib.bucket_rows(nr, 8)
        bst = rng.integers(0, 8, (E, n_pad, 4)).astype(np.uint8)
        yst = rng.normal(size=(E, n_pad)).astype(np.float32)
        mst = np.zeros((E, n_pad), np.float32)
        mst[:, :nr] = 1.0
        rngs = np.stack([np.asarray(jax.random.key_data(
            jax.random.PRNGKey(i)), np.uint32) for i in range(E)])
        spec = tree_impl.TreeSpec(max_depth=3, n_bins=8, n_features=4,
                                  feature_k=4, min_instances=1,
                                  min_info_gain=0.0, reg_lambda=0.0,
                                  gamma=0.0)
        es = tree_impl.EnsembleSpec(tree=spec, n_trees=2, loss="squared",
                                    boosting=False, bootstrap=False,
                                    subsample=1.0, step_size=0.1)
        dyn = (np.full(E, 3, np.int32), np.full(E, 4, np.int32),
               np.ones(E, np.float32), np.zeros(E, np.float32),
               np.zeros(E, bool), np.ones(E, np.float32))
        outs = {}
        for knob in (1, 8):
            GLOBAL_CONF.set("sml.cv.trialAxisDevices", knob)
            try:
                packs, bases = tree_impl.fit_ensembles_trials(
                    bst, yst, mst, es, rngs, *dyn)
            finally:
                GLOBAL_CONF.unset("sml.cv.trialAxisDevices")
            assert packs.shape[0] == E and bases.shape[0] == E
            outs[knob] = (packs, bases)
    np.testing.assert_allclose(outs[8][1], outs[1][1], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(outs[8][0], outs[1][0], rtol=1e-4,
                               atol=1e-4)


# --------------------------------------------- collective payload volume
def test_collective_psum_bytes_counted_and_on_trace(xy):
    """Per-op payload counters: a fresh tree program's trace counts
    `collective.psum` launches AND their byte volume; the bytes land on
    the Chrome-trace counter tracks."""
    from sml_tpu import obs
    from sml_tpu.ml import tree_impl
    from sml_tpu.obs._trace import to_trace_events

    X, y = xy
    GLOBAL_CONF.set("sml.obs.enabled", True)
    try:
        obs.reset()
        with _mesh(8):
            staged = tree_impl.stage_tree_data(X, y, max_bins=16)
            g = tree_impl.stage_aligned(-y, staged.n_padded)
            h = tree_impl.stage_aligned(np.ones_like(y), staged.n_padded)
            w = tree_impl.stage_aligned(np.ones_like(y), staged.n_padded)
            spec = tree_impl.TreeSpec(max_depth=3, n_bins=16, n_features=6,
                                      feature_k=6, min_instances=1,
                                      min_info_gain=0.0, reg_lambda=0.0,
                                      gamma=0.0)
            tree_impl.fit_tree(staged.binned_dev, g, h, w, spec)
        counters = obs.RECORDER.counters()
        assert counters.get("collective.psum", 0) >= 1
        assert counters.get("collective.psum_bytes", 0) > 0
        trace = to_trace_events(obs.RECORDER.events())
        tracks = {e["name"] for e in trace if e["ph"] == "C"}
        assert "collective.psum_bytes" in tracks
    finally:
        GLOBAL_CONF.set("sml.obs.enabled", False)


def test_hist_subtraction_halves_psum_payload(xy):
    """The histogram-subtraction trick is visible in the flight recorder:
    the same ensemble traced with subtraction ON moves fewer psum bytes
    per program than with it OFF (right children are parent - left,
    post-psum, so the below-root payload halves)."""
    from sml_tpu import obs
    from sml_tpu.ml import tree_impl
    from sml_tpu.ml._tree_models import _fit_ensemble

    X, y = xy
    GLOBAL_CONF.set("sml.obs.enabled", True)
    try:
        volumes = {}
        for sub in (True, False):
            GLOBAL_CONF.set("sml.tree.histSubtraction", sub)
            obs.reset()
            with _mesh(8):
                # fresh program per toggle (the setting is a cache key),
                # so trace-time counters fire for both variants
                _fit_ensemble(X, y, categorical={}, max_depth=4,
                              max_bins=16, min_instances=1,
                              min_info_gain=0.0, n_trees=2, feature_k=None,
                              bootstrap=False, subsample=1.0, seed=3,
                              loss="squared")
            volumes[sub] = obs.RECORDER.counters() \
                .get("collective.psum_bytes", 0.0)
    finally:
        GLOBAL_CONF.unset("sml.tree.histSubtraction")
        GLOBAL_CONF.set("sml.obs.enabled", False)
    assert 0 < volumes[True] < volumes[False]


# ----------------------------------------------------- prewarm mesh gating
def test_prewarm_foreign_manifest_skipped_on_8dev_mesh(spark, xy,
                                                       tmp_path):
    """A manifest recorded under a 1-device mesh signature must be
    SKIPPED when replayed on the 8-device mesh (and vice versa) — a
    first-dispatch on the wrong mesh would compile dead programs."""
    from sml_tpu.ml.regression import DecisionTreeRegressor
    from sml_tpu.parallel import prewarm

    prev = GLOBAL_CONF.get("sml.compile.cacheDir")
    GLOBAL_CONF.set("sml.compile.cacheDir", str(tmp_path))
    try:
        fdf = _frame(spark, *xy)
        with _mesh(8):
            DecisionTreeRegressor(labelCol="label", maxDepth=2,
                                  seed=1).fit(fdf)
        mpath = os.path.join(str(tmp_path), "prewarm_manifest.json")
        with open(mpath) as f:
            man = json.load(f)
        assert man["entries"]
        assert all(e["mesh"][0] == 8 for e in man["entries"].values())
        for e in man["entries"].values():
            e["mesh"] = [1, e["mesh"][1]]  # doctored: 1-device recording
        with open(mpath, "w") as f:
            json.dump(man, f)
        prewarm._state["entries"] = None
        with _mesh(8):
            stats = prewarm.prewarm()
        assert stats["programs"] == 0
        assert stats["skipped"] == len(man["entries"])
    finally:
        GLOBAL_CONF.set("sml.compile.cacheDir", prev or "")


# ------------------------------------------------------ dryrun regression
def test_dryrun_8dev_subprocess_exits_zero():
    """The CI gate for the MULTICHIP_r01 crash class: the 8-simulated-
    device dryrun runs end-to-end in a clean subprocess and exits 0 —
    mesh sizing from materialized devices, sharded staging, histogram
    trees, eval pushdown, ALS, KMeans, scorer forward, compact linear."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the dryrun provisions its own devices
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"),
         "--dryrun", "8"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "dryrun_multichip OK" in proc.stdout
