"""Test harness: run on a virtual 8-device CPU mesh.

Real multi-chip hardware is not available in CI; the sharding/collective
paths are validated on a host-local 8-device mesh the same way the course
relies on seeded determinism instead of a cluster (SURVEY §4).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The env-var route (JAX_PLATFORMS=cpu) is overridden by the axon TPU plugin
# in this image; the config API wins.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def spark():
    from sml_tpu import TpuSession
    return TpuSession.builder.appName("tests").getOrCreate()


@pytest.fixture()
def airbnb_pdf():
    """Synthetic SF-Airbnb-like dataset (the real one is blob-hosted and not
    redistributable in-tree); schema mirrors the course's cleaned table."""
    rng = np.random.default_rng(7)
    n = 2000
    neighbourhoods = ["Mission", "SoMa", "Sunset", "Richmond", "Castro", "Noe Valley"]
    room_types = ["Entire home/apt", "Private room", "Shared room"]
    bedrooms = rng.integers(0, 5, n).astype(float)
    accommodates = (bedrooms * 2 + rng.integers(1, 3, n)).astype(float)
    price = np.round(
        np.exp(4.0 + 0.35 * bedrooms + 0.08 * accommodates + rng.normal(0, 0.4, n)), 2)
    pdf = pd.DataFrame({
        "id": np.arange(n, dtype=np.int64),
        "neighbourhood_cleansed": rng.choice(neighbourhoods, n),
        "room_type": rng.choice(room_types, n, p=[0.6, 0.3, 0.1]),
        "bedrooms": bedrooms,
        "bathrooms": rng.choice([1.0, 1.5, 2.0, 2.5], n),
        "accommodates": accommodates,
        "number_of_reviews": rng.integers(0, 300, n).astype(float),
        "review_scores_rating": np.clip(rng.normal(93, 6, n), 20, 100),
        "minimum_nights": rng.integers(1, 30, n).astype(float),
        "price": price,
    })
    return pdf


@pytest.fixture()
def airbnb_df(spark, airbnb_pdf):
    return spark.createDataFrame(airbnb_pdf)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running perf/scale tests")
