"""Spark randomSplit sampler parity (frame/sampling.py).

Layers of evidence, mirroring the Murmur3 anchoring strategy:
- algorithm golden vectors for hashSeed / XORShiftRandom.nextDouble,
  pinned from the reference pure-python implementation (the published
  algorithm in core/.../util/random/XORShiftRandom.scala) — the native
  kernel must reproduce them bit-for-bit;
- structural properties Spark documents and the course demonstrates
  (`ML 02:38-52`): determinism, disjoint+exhaustive cells,
  partition-layout sensitivity, per-partition local sort.
"""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.frame.sampling import (XORShiftRandom, hash_seed,
                                    partition_uniforms, presplit_sort)

# hashSeed is MurmurHash3 (already externally anchored by
# tests/test_hashing.py against the course's own Spark constants) over
# the seed's 8 big-endian bytes; these pins freeze the composition.
HASH_SEED_VECTORS = {
    0: hash_seed(0),
    1: hash_seed(1),
    42: hash_seed(42),
    12345: hash_seed(12345),
}


def test_hash_seed_is_stable_and_64bit():
    for s, v in HASH_SEED_VECTORS.items():
        assert hash_seed(s) == v
        assert 0 <= v < (1 << 64)
    # distinct seeds scramble to distinct states
    assert len(set(HASH_SEED_VECTORS.values())) == len(HASH_SEED_VECTORS)


def test_next_double_reference_properties():
    rng = XORShiftRandom(42)
    draws = [rng.next_double() for _ in range(1000)]
    assert all(0.0 <= d < 1.0 for d in draws)
    # java.util.Random.nextDouble has 53-bit resolution
    assert len(set(draws)) == 1000
    # mean of 1000 uniforms within loose bounds
    assert 0.4 < float(np.mean(draws)) < 0.6


def test_native_kernel_matches_reference():
    from sml_tpu.frame.sampling import _xorshift_lib
    assert _xorshift_lib() is not None, \
        "native xorshift kernel failed to build — test would be tautological"
    for seed in (0, 1, 42, 977, 2**31 - 1):
        ref = XORShiftRandom(seed)
        expect = np.array([ref.next_double() for _ in range(257)])
        got = partition_uniforms(seed, 0, 257)
        np.testing.assert_array_equal(got, expect)


def test_partition_uniforms_seed_offset():
    """Spark seeds each partition's sampler with seed + partitionIndex."""
    np.testing.assert_array_equal(partition_uniforms(40, 2, 64),
                                  partition_uniforms(42, 0, 64))


def test_split_cells_disjoint_exhaustive(spark):
    pdf = pd.DataFrame({"a": np.arange(10_000, dtype=float),
                        "b": np.arange(10_000) % 7})
    df = spark.createDataFrame(pdf)
    a, b, c = df.randomSplit([0.5, 0.3, 0.2], seed=42)
    pa, pb, pc = a.toPandas(), b.toPandas(), c.toPandas()
    assert len(pa) + len(pb) + len(pc) == len(pdf)
    seen = np.concatenate([pa["a"], pb["a"], pc["a"]])
    assert len(np.unique(seen)) == len(pdf)
    # weights respected within sampling noise
    assert abs(len(pa) / len(pdf) - 0.5) < 0.02


def test_split_deterministic_and_memoized(spark):
    pdf = pd.DataFrame({"a": np.arange(5000, dtype=float)})
    df = spark.createDataFrame(pdf)
    t1, _ = df.randomSplit([0.8, 0.2], seed=42)
    t2, _ = df.randomSplit([0.8, 0.2], seed=42)
    assert t1 is t2  # plan-cache reuse of identical (weights, seed)
    t3, _ = df.randomSplit([0.8, 0.2], seed=43)
    assert t3 is not t1
    assert sorted(t1.toPandas()["a"]) != sorted(t3.toPandas()["a"])


def test_split_partition_sensitivity(spark):
    """The course's ML 02 lesson: same seed, different partition layout,
    different rows — because the per-partition RNG stream changes."""
    pdf = pd.DataFrame({"a": np.arange(20_000, dtype=float)})
    from sml_tpu.frame.dataframe import DataFrame
    df4 = DataFrame.from_pandas(pdf, num_partitions=4)
    df8 = DataFrame.from_pandas(pdf, num_partitions=8)
    a4, _ = df4.randomSplit([0.8, 0.2], seed=42)
    a8, _ = df8.randomSplit([0.8, 0.2], seed=42)
    s4 = set(a4.toPandas()["a"])
    s8 = set(a8.toPandas()["a"])
    assert s4 != s8
    # but both are deterministic for their layout
    assert set(df4.randomSplit([0.8, 0.2], seed=42)[0].toPandas()["a"]) == s4


def test_presplit_sort_orders_rows_nulls_first():
    pdf = pd.DataFrame({"x": [3.0, np.nan, 1.0, 2.0],
                        "s": ["d", "b", "c", "a"]})
    out = presplit_sort(pdf)
    assert np.isnan(out["x"].iloc[0])
    assert list(out["x"].iloc[1:]) == [1.0, 2.0, 3.0]


def test_legacy_sampler_conf(spark):
    from sml_tpu.conf import GLOBAL_CONF
    pdf = pd.DataFrame({"a": np.arange(4000, dtype=float)})
    df = spark.createDataFrame(pdf)
    spark_rows = set(df.randomSplit([0.8, 0.2], seed=7)[0].toPandas()["a"])
    GLOBAL_CONF.set("sml.split.sampler", "legacy")
    try:
        df2 = spark.createDataFrame(pdf)
        legacy_rows = set(
            df2.randomSplit([0.8, 0.2], seed=7)[0].toPandas()["a"])
    finally:
        GLOBAL_CONF.set("sml.split.sampler", "spark")
    assert legacy_rows != spark_rows  # distinct documented mechanisms
