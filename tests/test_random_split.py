"""Spark randomSplit sampler parity (frame/sampling.py).

Layers of evidence, mirroring the Murmur3 anchoring strategy:
- HARD-CODED golden vectors for hashSeed / XORShiftRandom.nextDouble
  (the published algorithm in core/.../util/random/XORShiftRandom.scala,
  64-byte hash buffer included), cross-derived through the independent
  native murmur3 kernel — the pure-python reference AND the native
  kernel must reproduce them bit-for-bit;
- pinned randomSplit row-index sets for fixed partition layouts;
- structural properties Spark documents and the course demonstrates
  (`ML 02:38-52`): determinism, disjoint+exhaustive cells,
  partition-layout sensitivity, per-partition local sort.
"""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.frame.sampling import (XORShiftRandom, hash_seed,
                                    partition_uniforms, presplit_sort)

# HARD-CODED hashSeed golden vectors (NOT recomputed from hash_seed at
# import time — a tautological pin can never catch a divergence). The
# values are XORShiftRandom.hashSeed over the 64-byte buffer Spark
# actually hashes (ByteBuffer.allocate(java.lang.Long.SIZE) allocates 64
# BYTES — the constant is in bits — so the 8 big-endian seed bytes ride
# with 56 zeros and length-64 finalization), cross-generated from the
# repo's independent C++ murmur3 kernel (native/murmur3.cc, itself
# anchored against the course's Spark hash() constants by
# tests/test_hashing.py) composed per the published hashSeed algorithm.
HASH_SEED_VECTORS = {
    0: 0x427B0291EEA8D4AE,
    1: 0xEB35A34DF420ED6F,
    42: 0xCEA176B6C35E99CF,
    12345: 0x1A5B3ACFF3616EB8,
}

# first nextDouble draws of the hashSeed-scrambled XORShift stream —
# java.util.Random's two-word construction over next(26)/next(27)
NEXT_DOUBLE_VECTORS = {
    0: [0.8446490682263027, 0.4048454303385226,
        0.5871875724155838, 0.8865128837019473],
    42: [0.6661236774413726, 0.8583151351252906,
         0.9139963682495181, 0.8664942556157945],
    12345: [0.3217855146445381, 0.5926558057691951,
            0.3530876039804548, 0.18715752944048802],
}


def test_hash_seed_matches_pinned_goldens():
    for s, v in HASH_SEED_VECTORS.items():
        assert hash_seed(s) == v, f"hashSeed({s}) diverged from pin"
        assert 0 <= v < (1 << 64)
    # distinct seeds scramble to distinct states
    assert len(set(HASH_SEED_VECTORS.values())) == len(HASH_SEED_VECTORS)


def test_hash_seed_matches_independent_murmur3_kernel():
    """Re-derive hashSeed through the independent C++ murmur3 (the hash()
    kernel anchored by test_hashing.py), composing the published
    algorithm: low = mm3(buf64, arraySeed); high = mm3(buf64, low)."""
    import ctypes

    from sml_tpu.native.build import load_library
    lib = load_library("murmur3")
    if lib is None:
        pytest.skip("native murmur3 kernel unavailable")
    lib.mm3_hash_one_bytes.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                       ctypes.c_int32]
    lib.mm3_hash_one_bytes.restype = ctypes.c_int32
    for s in (0, 1, 42, 977, 12345, 2**31 - 1):
        buf = (s & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big") + b"\x00" * 56
        low = lib.mm3_hash_one_bytes(
            buf, 64, ctypes.c_int32(0x3C074A61).value) & 0xFFFFFFFF
        high = lib.mm3_hash_one_bytes(
            buf, 64, ctypes.c_int32(
                low - (1 << 32) if low >= (1 << 31) else low).value) \
            & 0xFFFFFFFF
        assert hash_seed(s) == ((high << 32) | low)


def test_next_double_matches_pinned_goldens():
    for s, want in NEXT_DOUBLE_VECTORS.items():
        rng = XORShiftRandom(s)
        got = [rng.next_double() for _ in range(len(want))]
        assert got == want, f"nextDouble stream for seed {s} diverged"


def test_next_double_reference_properties():
    rng = XORShiftRandom(42)
    draws = [rng.next_double() for _ in range(1000)]
    assert all(0.0 <= d < 1.0 for d in draws)
    # java.util.Random.nextDouble has 53-bit resolution
    assert len(set(draws)) == 1000
    # mean of 1000 uniforms within loose bounds
    assert 0.4 < float(np.mean(draws)) < 0.6


# pinned randomSplit row-index sets: 100 rows [0..99], fixed partition
# layouts — the whole pipeline (pre-split sort → hashSeed → XORShift
# stream → BernoulliCellSampler cells) frozen as observable output. Any
# change to any stage moves these sets.
SPLIT_PINS = [
    # (num_partitions, weights, seed, sorted row ids of the LAST cell)
    (2, [0.8, 0.2], 42,
     [1, 2, 3, 13, 16, 35, 52, 55, 62, 68, 73, 80, 81, 82, 84, 85, 88,
      89, 91, 94, 99]),
    (4, [0.75, 0.25], 7,
     [0, 8, 9, 14, 15, 17, 21, 22, 23, 27, 29, 30, 38, 40, 41, 42, 45,
      47, 49, 56, 58, 59, 61, 66, 77, 83, 97]),
]


def test_random_split_row_sets_match_pins():
    from sml_tpu.frame.dataframe import DataFrame
    pdf = pd.DataFrame({"a": np.arange(100, dtype=float)})
    for nparts, weights, seed, want in SPLIT_PINS:
        df = DataFrame.from_pandas(pdf, num_partitions=nparts)
        cells = df.randomSplit(weights, seed=seed)
        got = sorted(int(v) for v in cells[-1].toPandas()["a"])
        assert got == want, \
            f"randomSplit pin drifted (parts={nparts}, seed={seed})"


def test_native_kernel_matches_reference():
    from sml_tpu.frame.sampling import _xorshift_lib
    assert _xorshift_lib() is not None, \
        "native xorshift kernel failed to build — test would be tautological"
    for seed in (0, 1, 42, 977, 2**31 - 1):
        ref = XORShiftRandom(seed)
        expect = np.array([ref.next_double() for _ in range(257)])
        got = partition_uniforms(seed, 0, 257)
        np.testing.assert_array_equal(got, expect)


def test_partition_uniforms_seed_offset():
    """Spark seeds each partition's sampler with seed + partitionIndex."""
    np.testing.assert_array_equal(partition_uniforms(40, 2, 64),
                                  partition_uniforms(42, 0, 64))


def test_split_cells_disjoint_exhaustive(spark):
    pdf = pd.DataFrame({"a": np.arange(10_000, dtype=float),
                        "b": np.arange(10_000) % 7})
    df = spark.createDataFrame(pdf)
    a, b, c = df.randomSplit([0.5, 0.3, 0.2], seed=42)
    pa, pb, pc = a.toPandas(), b.toPandas(), c.toPandas()
    assert len(pa) + len(pb) + len(pc) == len(pdf)
    seen = np.concatenate([pa["a"], pb["a"], pc["a"]])
    assert len(np.unique(seen)) == len(pdf)
    # weights respected within sampling noise
    assert abs(len(pa) / len(pdf) - 0.5) < 0.02


def test_split_deterministic_and_memoized(spark):
    pdf = pd.DataFrame({"a": np.arange(5000, dtype=float)})
    df = spark.createDataFrame(pdf)
    t1, _ = df.randomSplit([0.8, 0.2], seed=42)
    t2, _ = df.randomSplit([0.8, 0.2], seed=42)
    assert t1 is t2  # plan-cache reuse of identical (weights, seed)
    t3, _ = df.randomSplit([0.8, 0.2], seed=43)
    assert t3 is not t1
    assert sorted(t1.toPandas()["a"]) != sorted(t3.toPandas()["a"])


def test_split_partition_sensitivity(spark):
    """The course's ML 02 lesson: same seed, different partition layout,
    different rows — because the per-partition RNG stream changes."""
    pdf = pd.DataFrame({"a": np.arange(20_000, dtype=float)})
    from sml_tpu.frame.dataframe import DataFrame
    df4 = DataFrame.from_pandas(pdf, num_partitions=4)
    df8 = DataFrame.from_pandas(pdf, num_partitions=8)
    a4, _ = df4.randomSplit([0.8, 0.2], seed=42)
    a8, _ = df8.randomSplit([0.8, 0.2], seed=42)
    s4 = set(a4.toPandas()["a"])
    s8 = set(a8.toPandas()["a"])
    assert s4 != s8
    # but both are deterministic for their layout
    assert set(df4.randomSplit([0.8, 0.2], seed=42)[0].toPandas()["a"]) == s4


def test_presplit_sort_orders_rows_nulls_first():
    pdf = pd.DataFrame({"x": [3.0, np.nan, 1.0, 2.0],
                        "s": ["d", "b", "c", "a"]})
    out = presplit_sort(pdf)
    assert np.isnan(out["x"].iloc[0])
    assert list(out["x"].iloc[1:]) == [1.0, 2.0, 3.0]


def test_legacy_sampler_conf(spark):
    from sml_tpu.conf import GLOBAL_CONF
    pdf = pd.DataFrame({"a": np.arange(4000, dtype=float)})
    df = spark.createDataFrame(pdf)
    spark_rows = set(df.randomSplit([0.8, 0.2], seed=7)[0].toPandas()["a"])
    GLOBAL_CONF.set("sml.split.sampler", "legacy")
    try:
        df2 = spark.createDataFrame(pdf)
        legacy_rows = set(
            df2.randomSplit([0.8, 0.2], seed=7)[0].toPandas()["a"])
    finally:
        GLOBAL_CONF.set("sml.split.sampler", "spark")
    assert legacy_rows != spark_rows  # distinct documented mechanisms
