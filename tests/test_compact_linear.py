"""Compact (expand-on-device) linear paths vs the materialized block.

The scale path (`featurizer.CompactParts` + `linear_impl.fit_*_compact`)
must reproduce the standard path's fits: the Gram moments and IRLS steps
are the same math, only the one-hot expansion moves on-chip. Gated by
`sml.linear.compactBytes`, flipped per-case here.
"""

import numpy as np
import pytest

from sml_tpu.conf import GLOBAL_CONF
from sml_tpu.courseware import make_airbnb_dataset
from sml_tpu.ml import Pipeline
from sml_tpu.ml.classification import LogisticRegression
from sml_tpu.ml.feature import (Imputer, OneHotEncoder, StringIndexer,
                                VectorAssembler)
from sml_tpu.ml.regression import LinearRegression

CAT = ["neighbourhood_cleansed", "room_type", "property_type"]
NUM = ["accommodates", "bathrooms", "bedrooms", "beds",
       "minimum_nights", "number_of_reviews", "review_scores_rating"]


def _stages(est):
    idx = [c + "_idx" for c in CAT]
    ohe = [c + "_ohe" for c in CAT]
    imp = [c + "_imp" for c in NUM]
    return [
        Imputer(strategy="median", inputCols=NUM, outputCols=imp),
        StringIndexer(inputCols=CAT, outputCols=idx, handleInvalid="skip"),
        OneHotEncoder(inputCols=idx, outputCols=ohe),
        VectorAssembler(inputCols=ohe + imp, outputCol="features"),
        est,
    ]


@pytest.fixture
def frames(spark):
    pdf = make_airbnb_dataset(n=8000, seed=7)
    pdf_bin = pdf.copy()
    pdf_bin["label"] = (pdf_bin["price"]
                        > pdf_bin["price"].median()).astype(float)
    return spark.createDataFrame(pdf), spark.createDataFrame(pdf_bin)


@pytest.fixture
def compact_toggle():
    old = GLOBAL_CONF.get("sml.linear.compactBytes")
    yield lambda on: GLOBAL_CONF.set("sml.linear.compactBytes",
                                     0 if on else 1 << 40)
    GLOBAL_CONF.set("sml.linear.compactBytes", old)


def _coefs(model):
    tail = model.stages[-1]
    return tail.coefficients.toArray(), tail.intercept


def test_linear_compact_matches_materialized(frames, compact_toggle):
    df, _ = frames
    compact_toggle(False)
    c1, i1 = _coefs(Pipeline(stages=_stages(
        LinearRegression(labelCol="price"))).fit(df))
    compact_toggle(True)
    c2, i2 = _coefs(Pipeline(stages=_stages(
        LinearRegression(labelCol="price"))).fit(df))
    np.testing.assert_allclose(c1, c2, rtol=1e-5, atol=1e-5)
    assert abs(i1 - i2) < 1e-5


def test_elastic_net_runs_on_compact_gram(frames, compact_toggle):
    df, _ = frames
    est = lambda: LinearRegression(labelCol="price", regParam=0.1,  # noqa
                                   elasticNetParam=0.5)
    compact_toggle(False)
    c1, _ = _coefs(Pipeline(stages=_stages(est())).fit(df))
    compact_toggle(True)
    c2, _ = _coefs(Pipeline(stages=_stages(est())).fit(df))
    np.testing.assert_allclose(c1, c2, atol=1e-4)


def test_logistic_fused_irls_matches_host_loop(frames, compact_toggle):
    _, df = frames
    est = lambda: LogisticRegression(labelCol="label", maxIter=12)  # noqa
    compact_toggle(False)
    m1 = Pipeline(stages=_stages(est())).fit(df)
    compact_toggle(True)
    m2 = Pipeline(stages=_stages(est())).fit(df)
    c1, _ = _coefs(m1)
    c2, _ = _coefs(m2)
    np.testing.assert_allclose(c1, c2, atol=5e-4)
    s1, s2 = m1.stages[-1].summary, m2.stages[-1].summary
    assert abs(s1.accuracy - s2.accuracy) < 5e-3
    assert abs(s1.areaUnderROC - s2.areaUnderROC) < 5e-3


def test_penalized_logistic_falls_back_correctly(frames, compact_toggle):
    _, df = frames
    est = lambda: LogisticRegression(labelCol="label", maxIter=8,  # noqa
                                     regParam=0.01)
    compact_toggle(False)
    c1, _ = _coefs(Pipeline(stages=_stages(est())).fit(df))
    compact_toggle(True)  # compact attach + expand_host fallback
    c2, _ = _coefs(Pipeline(stages=_stages(est())).fit(df))
    np.testing.assert_allclose(c1, c2, atol=1e-5)


def test_compact_parts_expand_matches_block(frames):
    """CompactParts.expand_host reproduces the featurizer's block and
    predict_affine equals X @ w."""
    df, _ = frames
    from sml_tpu.ml.featurizer import CompiledFeaturizer
    stages = _stages(LinearRegression(labelCol="price"))
    fitted = [stages[0].fit(df), stages[1].fit(df)]
    ohe_m = stages[2]._fit_with_sizes if hasattr(stages[2], "_fit_with_sizes") \
        else None
    prep = Pipeline(stages=stages[:-1]).fit(df)
    feat = CompiledFeaturizer.from_stages(prep.stages[:-1], prep.stages[-1])
    assert feat is not None
    pdf = df.toPandas()
    parts = feat.compact_parts(pdf)
    assert parts is not None
    X, keep = feat.transform_with_mask(pdf)
    np.testing.assert_array_equal(parts.expand_host(), X)
    rng = np.random.default_rng(0)
    w = rng.normal(size=parts.width)
    np.testing.assert_allclose(parts.predict_affine(w, 1.5),
                               X.astype(np.float64) @ w + 1.5, rtol=1e-6)
    assert fitted and ohe_m is None  # silence lints; fixtures exercised
