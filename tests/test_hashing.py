import numpy as np
import pandas as pd

from sml_tpu.native import hashing
from sml_tpu.native.build import load_library


def test_spark_hash_course_constants():
    """The ONLY Spark-computed ground truth in this image: the course's
    hardcoded answer hashes (`Labs/ML 00L - Dedup Lab.py:89-90`, harness at
    `Includes/Class-Utility-Methods.py:161-211`). Spark evaluates
    `abs(hash(value)).cast("int")` with hash = Murmur3_x86_32(seed=42)
    over UTF-8 bytes with Spark's per-trailing-byte tail mix. If our
    Murmur3 drifts from Spark's in seed chaining, tail handling, or sign
    treatment, these externally-anchored vectors fail."""
    from sml_tpu.courseware import toHash

    assert toHash("8") == 1276280174
    assert toHash("100000") == 972882115
    # raw signed values feeding the abs (both negative in Spark)
    assert hashing._py_hash_bytes(b"8", hashing.SEED) == -1276280174
    assert hashing._py_hash_bytes(b"100000", hashing.SEED) == -972882115
    # the vectorized column kernel (native or numpy) agrees with the
    # scalar reference on the anchored vectors
    col = hashing.hash_column(pd.Series(["8", "100000"]),
                              np.full(2, hashing.SEED, dtype=np.int32))
    assert col.tolist() == [-1276280174, -972882115]


def test_murmur3_regression_pins():
    """Self-derived pins for the int/long/double/string paths — regression
    detectors for byte-order, width, and sign-extension changes (the
    string path's external anchor is test_spark_hash_course_constants)."""
    seeds = np.full(1, 42, dtype=np.int32)
    assert hashing._np_hash_int(np.array([0], np.int32), seeds.copy())[0] \
        == hashing._np_hash_int(np.array([0], np.int32), seeds.copy())[0]
    pins = {
        ("int", 0): int(hashing._np_hash_int(np.array([0], np.int32),
                                             seeds.copy())[0]),
        ("long", 0): int(hashing._np_hash_long(np.array([0], np.int64),
                                               seeds.copy())[0]),
    }
    assert pins[("int", 0)] != pins[("long", 0)]  # widths hash differently
    # byte-level goldens for the string kernel, covering 0-3 tail bytes
    # and sign-extension of high bytes (values pinned from this
    # implementation, which the course constants anchor externally)
    assert hashing._py_hash_bytes(b"", 42) == 142593372
    assert hashing._py_hash_bytes(b"abcd", 42) == -396302900
    assert hashing._py_hash_bytes("ü".encode("utf-8"), 42) == -1098725648


def test_int_long_double_consistency():
    seeds = np.full(3, 42, dtype=np.int32)
    h_long = hashing._np_hash_long(np.array([1, 2, 3], dtype=np.int64), seeds.copy())
    h_int = hashing._np_hash_int(np.array([1, 2, 3], dtype=np.int32), seeds.copy())
    assert not np.array_equal(h_long, h_int)  # widths hash differently
    # double hashes via long bits
    h_d = hashing._np_hash_double(np.array([1.0, 2.0, 3.0]), seeds.copy())
    bits = np.array([1.0, 2.0, 3.0]).view(np.int64)
    assert np.array_equal(h_d, hashing._np_hash_long(bits, seeds.copy()))


def test_negative_zero_normalized():
    seeds = np.full(2, 42, dtype=np.int32)
    h = hashing._np_hash_double(np.array([0.0, -0.0]), seeds)
    assert h[0] == h[1]


def test_string_native_matches_python_fallback():
    values = pd.Series(["hello", "", "a", "Spark ML", "ü日本", None])
    seeds = np.full(len(values), 42, dtype=np.int32)
    py = seeds.copy()
    for i, v in enumerate(values):
        if pd.isna(v):
            continue
        py[i] = hashing._py_hash_bytes(str(v).encode("utf-8"), int(py[i]))
    native = hashing.hash_column(values, seeds.copy())
    if load_library("murmur3") is not None:
        assert np.array_equal(py, native)
    else:
        assert np.array_equal(py, native)  # fallback path used twice


def test_multi_column_chaining():
    h1 = hashing.hash_columns([pd.Series([1, 2]), pd.Series(["a", "b"])])
    h2 = hashing.hash_columns([pd.Series(["a", "b"]), pd.Series([1, 2])])
    assert not np.array_equal(h1, h2)  # order matters (seed chaining)


def test_partition_ids_nonnegative():
    h = np.array([-5, -1, 0, 7, 123456], dtype=np.int32)
    ids = hashing.hash_partition_ids(h, 8)
    assert ((ids >= 0) & (ids < 8)).all()

