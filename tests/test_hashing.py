import numpy as np
import pandas as pd

from sml_tpu.native import hashing
from sml_tpu.native.build import load_library


def test_known_murmur3_vectors():
    """Golden vectors for Murmur3_x86_32 with per-trailing-byte tail and seed
    chaining (int path is the standard single-block murmur3)."""
    # standard murmur3_32("", seed) finalization over ints
    assert hashing.hash_scalar(np.int64(0)) == hashing.hash_scalar(np.int64(0))
    a = hashing.hash_scalar(np.int64(1))
    b = hashing.hash_scalar(np.int64(2))
    assert a != b


def test_int_long_double_consistency():
    seeds = np.full(3, 42, dtype=np.int32)
    h_long = hashing._np_hash_long(np.array([1, 2, 3], dtype=np.int64), seeds.copy())
    h_int = hashing._np_hash_int(np.array([1, 2, 3], dtype=np.int32), seeds.copy())
    assert not np.array_equal(h_long, h_int)  # widths hash differently
    # double hashes via long bits
    h_d = hashing._np_hash_double(np.array([1.0, 2.0, 3.0]), seeds.copy())
    bits = np.array([1.0, 2.0, 3.0]).view(np.int64)
    assert np.array_equal(h_d, hashing._np_hash_long(bits, seeds.copy()))


def test_negative_zero_normalized():
    seeds = np.full(2, 42, dtype=np.int32)
    h = hashing._np_hash_double(np.array([0.0, -0.0]), seeds)
    assert h[0] == h[1]


def test_string_native_matches_python_fallback():
    values = pd.Series(["hello", "", "a", "Spark ML", "ü日本", None])
    seeds = np.full(len(values), 42, dtype=np.int32)
    py = seeds.copy()
    for i, v in enumerate(values):
        if pd.isna(v):
            continue
        py[i] = hashing._py_hash_bytes(str(v).encode("utf-8"), int(py[i]))
    native = hashing.hash_column(values, seeds.copy())
    if load_library("murmur3") is not None:
        assert np.array_equal(py, native)
    else:
        assert np.array_equal(py, native)  # fallback path used twice


def test_multi_column_chaining():
    h1 = hashing.hash_columns([pd.Series([1, 2]), pd.Series(["a", "b"])])
    h2 = hashing.hash_columns([pd.Series(["a", "b"]), pd.Series([1, 2])])
    assert not np.array_equal(h1, h2)  # order matters (seed chaining)


def test_partition_ids_nonnegative():
    h = np.array([-5, -1, 0, 7, 123456], dtype=np.int32)
    ids = hashing.hash_partition_ids(h, 8)
    assert ((ids >= 0) & (ids < 8)).all()

