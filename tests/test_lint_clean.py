"""CI enforcement (PR 3): the committed tree must pass graftlint, the
linter must run jax-free from a cold interpreter, and the bench harness
must refuse to record from a dirty tree (`bench.py --lint`)."""

import json
import os
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RUNNER = os.path.join(REPO, "scripts", "graftlint.py")


def test_graftlint_clean_and_jax_free():
    """One subprocess proves both acceptance criteria: exit 0 on the
    repo with >=6 active rules, and no jax import anywhere in the lint
    path (the probe would raise before printing)."""
    probe = (
        "import importlib.util, json, sys\n"
        f"spec = importlib.util.spec_from_file_location('_g', {RUNNER!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "rc = m.main(['--json'])\n"
        "assert 'jax' not in sys.modules, 'linter imported jax'\n"
        "assert 'sml_tpu' not in sys.modules, 'linter imported sml_tpu'\n"
        "sys.exit(rc)\n")
    out = subprocess.run([sys.executable, "-c", probe], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert len(payload["rules"]) >= 6
    assert payload["violations"] == []


def test_single_rule_run_is_clean_on_committed_tree():
    """`--rule NAME` must exit 0 on the clean tree: suppressions owned
    by the rules that did NOT run are out of scope (review finding)."""
    out = subprocess.run([sys.executable, RUNNER, "--rule",
                          "conf-key-registry"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def test_update_baseline_preserves_reviewed_entries(tmp_path):
    """--update-baseline on the clean tree must re-emit the reviewed
    timeseries entries (reasons intact), not erase them because the old
    baseline already suppressed them (review finding)."""
    for d in ("sml_tpu", "scripts"):
        shutil.copytree(os.path.join(REPO, d), tmp_path / d,
                        ignore=shutil.ignore_patterns("__pycache__"))
    for f in ("bench.py", ".graftlint-baseline.json"):
        shutil.copy(os.path.join(REPO, f), tmp_path / f)
    os.makedirs(tmp_path / "tests")
    out = subprocess.run(
        [sys.executable, str(tmp_path / "scripts" / "graftlint.py"),
         "--update-baseline", "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    with open(tmp_path / ".graftlint-baseline.json") as fh:
        entries = json.load(fh)["entries"]
    assert len(entries) == 3, entries
    assert all(e["file"] == "sml_tpu/timeseries.py" for e in entries)
    assert all(not e["reason"].startswith("TODO") for e in entries)
    # and the refreshed baseline still passes the lint
    out2 = subprocess.run(
        [sys.executable, str(tmp_path / "scripts" / "graftlint.py"),
         "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0, out2.stdout + out2.stderr


def test_graftlint_json_reports_suppressions():
    out = subprocess.run([sys.executable, RUNNER, "--json"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    # every carried suppression is visible in the machine output
    assert payload["suppressed"]["baseline"] >= 1
    assert payload["suppressed"]["pragma"] >= 1


def test_bench_lint_gate_refuses_dirty_tree(tmp_path):
    """Copy the lintable surface, inject a violation, and check
    `bench.py --lint` exits 1 with the refusal message BEFORE doing any
    bench work (bench imports only numpy at module level, so this is a
    sub-second subprocess)."""
    for d in ("sml_tpu", "scripts"):
        shutil.copytree(os.path.join(REPO, d), tmp_path / d,
                        ignore=shutil.ignore_patterns("__pycache__"))
    for f in ("bench.py", ".graftlint-baseline.json"):
        shutil.copy(os.path.join(REPO, f), tmp_path / f)
    os.makedirs(tmp_path / "tests")
    rogue = tmp_path / "sml_tpu" / "rogue.py"
    rogue.write_text("import time\nT0 = time.time()\n")
    out = subprocess.run([sys.executable, "bench.py", "--lint"],
                         cwd=tmp_path, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "refusing to record" in out.stderr
    assert "rogue.py" in out.stdout
    # and the same tree passes once the violation is gone
    rogue.unlink()
    probe = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('_g', "
        "'scripts/graftlint.py')\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "sys.exit(m.main([]))\n")
    out2 = subprocess.run([sys.executable, "-c", probe], cwd=tmp_path,
                          capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0, out2.stdout + out2.stderr
