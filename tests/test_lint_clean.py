"""CI enforcement (PR 3): the committed tree must pass graftlint, the
linter must run jax-free from a cold interpreter, and the bench harness
must refuse to record from a dirty tree (`bench.py --lint`)."""

import json
import os
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RUNNER = os.path.join(REPO, "scripts", "graftlint.py")


def test_graftlint_clean_and_jax_free():
    """One subprocess proves both acceptance criteria: exit 0 on the
    repo with >=6 active rules, and no jax import anywhere in the lint
    path (the probe would raise before printing)."""
    probe = (
        "import importlib.util, json, sys\n"
        f"spec = importlib.util.spec_from_file_location('_g', {RUNNER!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "rc = m.main(['--json'])\n"
        "assert 'jax' not in sys.modules, 'linter imported jax'\n"
        "assert 'sml_tpu' not in sys.modules, 'linter imported sml_tpu'\n"
        "sys.exit(rc)\n")
    out = subprocess.run([sys.executable, "-c", probe], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert len(payload["rules"]) >= 6
    assert payload["violations"] == []


def test_single_rule_run_is_clean_on_committed_tree():
    """`--rule NAME` must exit 0 on the clean tree: suppressions owned
    by the rules that did NOT run are out of scope (review finding)."""
    out = subprocess.run([sys.executable, RUNNER, "--rule",
                          "conf-key-registry"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def test_update_baseline_preserves_reviewed_entries(tmp_path):
    """--update-baseline on the clean tree must re-emit the reviewed
    timeseries entries (reasons intact), not erase them because the old
    baseline already suppressed them (review finding)."""
    for d in ("sml_tpu", "scripts"):
        shutil.copytree(os.path.join(REPO, d), tmp_path / d,
                        ignore=shutil.ignore_patterns("__pycache__"))
    for f in ("bench.py", ".graftlint-baseline.json"):
        shutil.copy(os.path.join(REPO, f), tmp_path / f)
    os.makedirs(tmp_path / "tests")
    out = subprocess.run(
        [sys.executable, str(tmp_path / "scripts" / "graftlint.py"),
         "--update-baseline", "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    with open(tmp_path / ".graftlint-baseline.json") as fh:
        entries = json.load(fh)["entries"]
    assert len(entries) == 3, entries
    assert all(e["file"] == "sml_tpu/timeseries.py" for e in entries)
    assert all(not e["reason"].startswith("TODO") for e in entries)
    # and the refreshed baseline still passes the lint
    out2 = subprocess.run(
        [sys.executable, str(tmp_path / "scripts" / "graftlint.py"),
         "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0, out2.stdout + out2.stderr


def test_graftlint_json_reports_suppressions():
    out = subprocess.run([sys.executable, RUNNER, "--json"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    # every carried suppression is visible in the machine output
    assert payload["suppressed"]["baseline"] >= 1
    assert payload["suppressed"]["pragma"] >= 1


def test_exit_code_contract(tmp_path, capsys):
    """The documented contract (scripts/graftlint.py docstring): 0
    clean, 1 violations, 2 usage/internal error — relied on by the
    bench gate and CI. All three legs drive main() itself."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("_g_contract", RUNNER)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert m.main([]) == 0                          # clean tree
    assert m.main(["--rule", "no-such-rule"]) == 2  # usage error
    assert m.main(["--list-rules"]) == 0
    # violations -> 1: a minimal violated tree under --root (absent
    # targets are simply empty)
    os.makedirs(tmp_path / "sml_tpu")
    (tmp_path / "sml_tpu" / "a.py").write_text(
        "import time\nt = time.time()\n")
    capsys.readouterr()
    assert m.main(["--root", str(tmp_path)]) == 1
    assert "no-wallclock-in-engine" in capsys.readouterr().out
    out = subprocess.run([sys.executable, RUNNER, "--rule", "bogus"],
                         cwd=REPO, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 2


def test_regress_flags_lint_block_loss_and_violation_growth():
    """obs/regress.py judges the sidecar `lint` block: a vanished block
    (sidecar candidates), an unsuppressed-violation increase, or an
    active-rule-count decrease each flag as a regression."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_regress_lint", os.path.join(REPO, "sml_tpu", "obs",
                                      "regress.py"))
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)
    lint_block = {"rules": 10, "files": 104, "violations": 0,
                  "suppressed_pragma": 88, "suppressed_baseline": 3}
    base = regress.normalize({"legs": {}, "lint": dict(lint_block)})
    same = regress.normalize({"legs": {}, "lint": dict(lint_block)})
    assert regress.compare(base, same)["ok"]
    gone = regress.normalize({"legs": {}})
    res = regress.compare(base, gone)
    assert not res["ok"]
    assert any(f["kind"] == "missing-lint-block"
               for f in res["regressions"])
    dirty = regress.normalize({"legs": {},
                               "lint": dict(lint_block, violations=2)})
    res2 = regress.compare(base, dirty)
    assert any(f["kind"] == "lint-violations" for f in res2["regressions"])
    shrunk = regress.normalize({"legs": {},
                                "lint": dict(lint_block, rules=9)})
    res3 = regress.compare(base, shrunk)
    assert any(f["kind"] == "lint-rules" for f in res3["regressions"])
    # driver records can never carry the block: exempt from coverage
    rec = regress.normalize({"parsed": {}, "tail": ""})
    assert regress.compare(base, rec)["ok"]


def test_bench_lint_gate_refuses_dirty_tree(tmp_path):
    """Copy the lintable surface, inject a violation, and check
    `bench.py --lint` exits 1 with the refusal message BEFORE doing any
    bench work (bench imports only numpy at module level, so this is a
    sub-second subprocess)."""
    for d in ("sml_tpu", "scripts"):
        shutil.copytree(os.path.join(REPO, d), tmp_path / d,
                        ignore=shutil.ignore_patterns("__pycache__"))
    for f in ("bench.py", ".graftlint-baseline.json"):
        shutil.copy(os.path.join(REPO, f), tmp_path / f)
    os.makedirs(tmp_path / "tests")
    rogue = tmp_path / "sml_tpu" / "rogue.py"
    rogue.write_text("import time\nT0 = time.time()\n")
    out = subprocess.run([sys.executable, "bench.py", "--lint"],
                         cwd=tmp_path, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "refusing to record" in out.stderr
    assert "rogue.py" in out.stdout
    # and the same tree passes once the violation is gone
    rogue.unlink()
    probe = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('_g', "
        "'scripts/graftlint.py')\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "sys.exit(m.main([]))\n")
    out2 = subprocess.run([sys.executable, "-c", probe], cwd=tmp_path,
                          capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0, out2.stdout + out2.stderr
