"""CI enforcement (PR 3): the committed tree must pass graftlint, the
linter must run jax-free from a cold interpreter, and the bench harness
must refuse to record from a dirty tree (`bench.py --lint`)."""

import json
import os
import shutil
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
RUNNER = os.path.join(REPO, "scripts", "graftlint.py")


def test_graftlint_clean_and_jax_free():
    """One subprocess proves both acceptance criteria: exit 0 on the
    repo with >=6 active rules, and no jax import anywhere in the lint
    path (the probe would raise before printing)."""
    probe = (
        "import importlib.util, json, sys\n"
        f"spec = importlib.util.spec_from_file_location('_g', {RUNNER!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "rc = m.main(['--json'])\n"
        "assert 'jax' not in sys.modules, 'linter imported jax'\n"
        "assert 'sml_tpu' not in sys.modules, 'linter imported sml_tpu'\n"
        "assert 'graftlint.traced' in sys.modules, "
        "'traced-region core not loaded standalone'\n"
        "assert 'graftlint.threads' in sys.modules, "
        "'thread-role core not loaded standalone'\n"
        "sys.exit(rc)\n")
    out = subprocess.run([sys.executable, "-c", probe], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert len(payload["rules"]) >= 6
    assert payload["violations"] == []
    # the extended machine surface: per-rule wall time for every active
    # rule, and per-violation status lists (active list is empty on the
    # clean tree; the suppressed list carries pragma/baseline entries)
    assert set(payload["rule_times"]) == set(payload["rules"])
    assert all(t >= 0 for t in payload["rule_times"].values())
    assert payload["suppressed_violations"], "suppression detail missing"
    assert {sv["status"] for sv in payload["suppressed_violations"]} \
        <= {"pragma", "baseline"}
    n_pragma = sum(1 for sv in payload["suppressed_violations"]
                   if sv["status"] == "pragma")
    assert n_pragma == payload["suppressed"]["pragma"]


def test_single_rule_run_is_clean_on_committed_tree():
    """`--rule NAME` must exit 0 on the clean tree: suppressions owned
    by the rules that did NOT run are out of scope (review finding)."""
    out = subprocess.run([sys.executable, RUNNER, "--rule",
                          "conf-key-registry"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr


def test_update_baseline_preserves_reviewed_entries(tmp_path):
    """--update-baseline on the clean tree must re-emit the reviewed
    timeseries entries (reasons intact), not erase them because the old
    baseline already suppressed them (review finding)."""
    for d in ("sml_tpu", "scripts"):
        shutil.copytree(os.path.join(REPO, d), tmp_path / d,
                        ignore=shutil.ignore_patterns("__pycache__"))
    for f in ("bench.py", ".graftlint-baseline.json"):
        shutil.copy(os.path.join(REPO, f), tmp_path / f)
    os.makedirs(tmp_path / "tests")
    out = subprocess.run(
        [sys.executable, str(tmp_path / "scripts" / "graftlint.py"),
         "--update-baseline", "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    with open(tmp_path / ".graftlint-baseline.json") as fh:
        entries = json.load(fh)["entries"]
    assert len(entries) == 3, entries
    assert all(e["file"] == "sml_tpu/timeseries.py" for e in entries)
    assert all(not e["reason"].startswith("TODO") for e in entries)
    # and the refreshed baseline still passes the lint
    out2 = subprocess.run(
        [sys.executable, str(tmp_path / "scripts" / "graftlint.py"),
         "--root", str(tmp_path)],
        cwd=tmp_path, capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0, out2.stdout + out2.stderr


def test_graftlint_json_reports_suppressions():
    out = subprocess.run([sys.executable, RUNNER, "--json"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    # every carried suppression is visible in the machine output
    assert payload["suppressed"]["baseline"] >= 1
    assert payload["suppressed"]["pragma"] >= 1


def test_exit_code_contract(tmp_path, capsys):
    """The documented contract (scripts/graftlint.py docstring): 0
    clean, 1 violations, 2 usage/internal error — relied on by the
    bench gate and CI. All three legs drive main() itself."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("_g_contract", RUNNER)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert m.main([]) == 0                          # clean tree
    assert m.main(["--rule", "no-such-rule"]) == 2  # usage error
    assert m.main(["--list-rules"]) == 0
    # violations -> 1: a minimal violated tree under --root (absent
    # targets are simply empty)
    os.makedirs(tmp_path / "sml_tpu")
    (tmp_path / "sml_tpu" / "a.py").write_text(
        "import time\nt = time.time()\n")
    capsys.readouterr()
    assert m.main(["--root", str(tmp_path)]) == 1
    assert "no-wallclock-in-engine" in capsys.readouterr().out
    out = subprocess.run([sys.executable, RUNNER, "--rule", "bogus"],
                         cwd=REPO, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 2


def test_changed_only_mode():
    """--changed-only keeps the exit-code contract: 0 on the clean tree
    against HEAD, 2 on a ref git cannot resolve; --json records the
    filter ref. The full tree is still analysed (cross-file rules), so
    the rule list stays complete."""
    out = subprocess.run([sys.executable, RUNNER, "--changed-only",
                          "HEAD", "--json"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True
    assert payload["changed_only"] == "HEAD"
    assert len(payload["rules"]) >= 14
    bad = subprocess.run([sys.executable, RUNNER, "--changed-only",
                          "no-such-ref-xyz"], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert bad.returncode == 2, bad.stdout + bad.stderr
    assert "--changed-only" in bad.stderr


def test_changed_only_filters_to_changed_files(tmp_path):
    """In a scratch git repo: a committed violation plus a changed-file
    violation — full run reports both (exit 1), --changed-only HEAD
    reports ONLY the changed file's, and a run scoped to an unchanged
    ref-clean file reports none."""
    import shutil as _sh
    if _sh.which("git") is None:
        pytest.skip("git unavailable")
    _sh.copytree(os.path.join(REPO, "scripts"), tmp_path / "scripts",
                 ignore=_sh.ignore_patterns("__pycache__"))
    _sh.copytree(os.path.join(REPO, "sml_tpu", "lint"),
                 tmp_path / "sml_tpu" / "lint",
                 ignore=_sh.ignore_patterns("__pycache__"))
    os.makedirs(tmp_path / "sml_tpu" / "obs")
    _sh.copy(os.path.join(REPO, "sml_tpu", "obs", "taxonomy.py"),
             tmp_path / "sml_tpu" / "obs" / "taxonomy.py")
    (tmp_path / "sml_tpu" / "old.py").write_text(
        "import time\nT0 = time.time()\n")
    env = dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
               GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t")

    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       env=env, capture_output=True, timeout=30)

    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    (tmp_path / "sml_tpu" / "new.py").write_text(
        "import time\nT1 = time.time()\n")
    runner = str(tmp_path / "scripts" / "graftlint.py")
    full = subprocess.run([sys.executable, runner, "--root",
                           str(tmp_path), "--json"], cwd=tmp_path,
                          capture_output=True, text=True, timeout=120)
    assert full.returncode == 1
    full_paths = {v["path"] for v in json.loads(full.stdout)["violations"]}
    assert {"sml_tpu/old.py", "sml_tpu/new.py"} <= full_paths
    part = subprocess.run([sys.executable, runner, "--root",
                           str(tmp_path), "--changed-only", "HEAD",
                           "--json"], cwd=tmp_path, capture_output=True,
                          text=True, timeout=120)
    assert part.returncode == 1
    part_paths = {v["path"] for v in json.loads(part.stdout)["violations"]}
    assert "sml_tpu/new.py" in part_paths
    assert "sml_tpu/old.py" not in part_paths


def test_regress_flags_lint_block_loss_and_violation_growth():
    """obs/regress.py judges the sidecar `lint` block: a vanished block
    (sidecar candidates), an unsuppressed-violation increase, or an
    active-rule-count decrease each flag as a regression."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_regress_lint", os.path.join(REPO, "sml_tpu", "obs",
                                      "regress.py"))
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)
    lint_block = {"rules": 14, "files": 119, "violations": 0,
                  "suppressed_pragma": 88, "suppressed_baseline": 3}
    base = regress.normalize({"legs": {}, "lint": dict(lint_block)})
    same = regress.normalize({"legs": {}, "lint": dict(lint_block)})
    assert regress.compare(base, same)["ok"]
    gone = regress.normalize({"legs": {}})
    res = regress.compare(base, gone)
    assert not res["ok"]
    assert any(f["kind"] == "missing-lint-block"
               for f in res["regressions"])
    dirty = regress.normalize({"legs": {},
                               "lint": dict(lint_block, violations=2)})
    res2 = regress.compare(base, dirty)
    assert any(f["kind"] == "lint-violations" for f in res2["regressions"])
    shrunk = regress.normalize({"legs": {},
                                "lint": dict(lint_block, rules=9)})
    res3 = regress.compare(base, shrunk)
    assert any(f["kind"] == "lint-rules" for f in res3["regressions"])
    # driver records can never carry the block: exempt from coverage
    rec = regress.normalize({"parsed": {}, "tail": ""})
    assert regress.compare(base, rec)["ok"]
    # absolute >=14-rule floor, judged even against a pre-PR-18 base
    # record that carried fewer rules
    old_base = regress.normalize({"legs": {},
                                  "lint": dict(lint_block, rules=10)})
    below = regress.normalize({"legs": {},
                               "lint": dict(lint_block, rules=13)})
    res4 = regress.compare(old_base, below)
    assert any(f["kind"] == "lint-rule-floor" for f in res4["regressions"])
    # untracked-compile-input is exact-mode: ONE occurrence regresses,
    # even when the total violation count did not grow vs base
    uci = regress.normalize({"legs": {}, "lint": dict(
        lint_block, violations=0,
        violations_by_rule={"untracked-compile-input": 1})})
    res5 = regress.compare(base, uci)
    assert any(f["kind"] == "lint-compile-input"
               for f in res5["regressions"])
    clean_by_rule = regress.normalize({"legs": {}, "lint": dict(
        lint_block, violations_by_rule={})})
    assert regress.compare(base, clean_by_rule)["ok"]


def test_bench_lint_gate_refuses_dirty_tree(tmp_path):
    """Copy the lintable surface, inject a violation, and check
    `bench.py --lint` exits 1 with the refusal message BEFORE doing any
    bench work (bench imports only numpy at module level, so this is a
    sub-second subprocess)."""
    for d in ("sml_tpu", "scripts"):
        shutil.copytree(os.path.join(REPO, d), tmp_path / d,
                        ignore=shutil.ignore_patterns("__pycache__"))
    for f in ("bench.py", ".graftlint-baseline.json"):
        shutil.copy(os.path.join(REPO, f), tmp_path / f)
    os.makedirs(tmp_path / "tests")
    rogue = tmp_path / "sml_tpu" / "rogue.py"
    rogue.write_text("import time\nT0 = time.time()\n")
    out = subprocess.run([sys.executable, "bench.py", "--lint"],
                         cwd=tmp_path, capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "refusing to record" in out.stderr
    assert "rogue.py" in out.stdout
    # and the same tree passes once the violation is gone
    rogue.unlink()
    probe = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('_g', "
        "'scripts/graftlint.py')\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "sys.exit(m.main([]))\n")
    out2 = subprocess.run([sys.executable, "-c", probe], cwd=tmp_path,
                          capture_output=True, text=True, timeout=120)
    assert out2.returncode == 0, out2.stdout + out2.stderr
