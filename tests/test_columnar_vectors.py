"""Columnar vector columns (VectorArray) + round-2 correctness fixes.

The r1 hot path built a Python DenseVector per row and re-stacked them per
fit; vector columns are now one dense (n, d) block behind a pandas
ExtensionArray, and staging is zero-copy (VERDICT r1 weak #3).
"""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.ml.linalg import (DenseVector, SparseVector, VectorArray,
                               to_matrix, vector_series)


def test_vector_array_basics():
    block = np.arange(12, dtype=np.float64).reshape(4, 3)
    arr = VectorArray(block)
    assert len(arr) == 4
    assert arr.width == 3
    v = arr[1]
    assert isinstance(v, DenseVector)
    assert np.array_equal(v.toArray(), [3, 4, 5])
    # block access is the same memory — no copies
    assert arr.block is block


def test_vector_array_take_filter_concat():
    a = VectorArray(np.eye(3))
    b = VectorArray(np.ones((2, 3)))
    s = pd.concat([pd.Series(a), pd.Series(b)], ignore_index=True)
    assert isinstance(s.array, VectorArray)
    assert s.array.block.shape == (5, 3)
    mask = np.array([True, False, True, False, True])
    filtered = s[mask].reset_index(drop=True)
    assert isinstance(filtered.array, VectorArray)
    assert np.array_equal(filtered.array.block[2], [1, 1, 1])


def test_vector_array_na_and_sparse_elements():
    block = np.array([[1.0, 0.0], [np.nan, np.nan], [0.0, 2.0]])
    arr = VectorArray(block, na=np.array([False, True, False]), sparse=True)
    assert arr[1] is None
    v = arr[2]
    assert isinstance(v, SparseVector)
    assert v.size == 2 and v[1] == 2.0
    assert list(arr.isna()) == [False, True, False]


def test_to_matrix_zero_copy_for_columnar():
    block = np.random.default_rng(0).normal(size=(10, 4))
    arr = VectorArray(block)
    assert to_matrix(arr) is block  # THE point: no per-row objects, no copy
    s = vector_series(block)
    # through a Series the block is handed over without per-row work
    # (pandas may shallow-copy the EA wrapper, not the data)
    assert np.shares_memory(to_matrix(s), s.array.block)


def test_assembler_output_is_columnar(spark, airbnb_pdf):
    from sml_tpu.ml.feature import VectorAssembler
    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                         outputCol="features")
    pdf = va.transform(df).toPandas()
    assert isinstance(pdf["features"].array, VectorArray)
    assert pdf["features"].array.block.shape == (len(airbnb_pdf), 2)
    assert isinstance(pdf["features"].iloc[0], DenseVector)


def test_ohe_output_columnar_sparse(spark):
    from sml_tpu.ml.feature import OneHotEncoder, StringIndexer
    pdf = pd.DataFrame({"c": ["a", "b", "a", "c", "b", "a"]})
    df = spark.createDataFrame(pdf)
    idx = StringIndexer(inputCol="c", outputCol="ci").fit(df).transform(df)
    out = OneHotEncoder(inputCols=["ci"], outputCols=["cv"]) \
        .fit(idx).transform(idx).toPandas()
    arr = out["cv"].array
    assert isinstance(arr, VectorArray)
    assert arr.block.shape == (6, 2)  # 3 categories, dropLast
    v = out["cv"].iloc[0]  # most frequent label "a" → index 0
    assert isinstance(v, SparseVector)
    assert np.array_equal(v.toArray(), [1.0, 0.0])


def test_reassembling_assembled_column_width(spark, airbnb_pdf):
    """ADVICE r1: re-assembling a previously assembled vector column must
    account for its true width in the slot metadata."""
    from sml_tpu.ml.feature import VectorAssembler
    df = spark.createDataFrame(airbnb_pdf)
    va1 = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                          outputCol="pair")
    step1 = va1.transform(df)
    va2 = VectorAssembler(inputCols=["pair", "bathrooms"], outputCol="features")
    step2 = va2.transform(step1)
    attrs = step2._ml_attrs["features"]
    assert attrs["numFeatures"] == 3
    pdf = step2.toPandas()
    assert pdf["features"].array.block.shape[1] == 3


def test_scaler_columnar(spark, airbnb_pdf):
    from sml_tpu.ml.feature import StandardScaler, VectorAssembler
    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                         outputCol="features")
    fdf = va.transform(df)
    scaled = StandardScaler(inputCol="features", outputCol="scaled",
                            withMean=True).fit(fdf).transform(fdf).toPandas()
    blk = scaled["scaled"].array.block
    # fit stages features as float32 (HBM dtype) — tolerances to match
    np.testing.assert_allclose(blk.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(blk.std(axis=0, ddof=1), 1.0, atol=1e-5)


def test_ridge_penalty_matches_fista_semantics():
    """ADVICE r1 (medium): closed-form ridge must penalize standardized
    coefficients like the FISTA elastic-net branch — α→0 continuity."""
    from sml_tpu.ml.linear_impl import fit_linear
    rng = np.random.default_rng(3)
    n = 4000
    X = np.stack([rng.normal(0, 10.0, n),      # large-variance feature
                  rng.normal(0, 0.1, n)], axis=1)  # small-variance feature
    y = 0.5 * X[:, 0] + 20.0 * X[:, 1] + rng.normal(0, 0.5, n)
    closed = fit_linear(X, y, regParam=1.0, elasticNetParam=0.0)
    fista = fit_linear(X, y, regParam=1.0, elasticNetParam=1e-9, maxIter=2000)
    np.testing.assert_allclose(closed.coefficients, fista.coefficients,
                               rtol=5e-3, atol=5e-4)


def test_logistic_penalty_standardized():
    """L2 logistic penalty scales with feature variance (reference
    standardization=True): scaling a feature by c scales its coefficient by
    ~1/c under the same regParam."""
    from sml_tpu.ml.linear_impl import fit_logistic
    rng = np.random.default_rng(5)
    n = 3000
    x = rng.normal(0, 1.0, n)
    y = (x + rng.normal(0, 1.0, n) > 0).astype(np.float32)
    f1 = fit_logistic(x[:, None].astype(np.float32), y, regParam=0.5)
    f100 = fit_logistic((x * 100.0)[:, None].astype(np.float32), y, regParam=0.5)
    assert f1.coefficients[0] == pytest.approx(f100.coefficients[0] * 100.0,
                                               rel=1e-2)


def test_prophet_future_only_predict():
    """ADVICE r1: predicting a future-only frame must keep the fitted
    seasonality blocks instead of re-gating on the prediction span."""
    from sml_tpu.timeseries import Prophet
    ds = pd.date_range("2020-01-01", periods=200, freq="D")
    y = 10 + 0.05 * np.arange(200) + 2 * np.sin(2 * np.pi * np.arange(200) / 7)
    m = Prophet().fit(pd.DataFrame({"ds": ds, "y": y}))
    assert "weekly" in m._block_names
    future = pd.DataFrame(
        {"ds": pd.date_range("2020-07-20", periods=5, freq="D")})
    fc = m.predict(future)   # 5-day span < 14-day auto gate — crashed in r1
    assert len(fc) == 5
    assert np.all(np.isfinite(fc["yhat"]))
    assert "weekly" in fc.columns
