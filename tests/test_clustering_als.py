"""KMeans (MLE 02) and ALS (MLE 01) behaviors on the CPU mesh."""

import numpy as np
import pandas as pd
import pytest

from sml_tpu.ml.clustering import KMeans, KMeansModel
from sml_tpu.ml.evaluation import RegressionEvaluator
from sml_tpu.ml.feature import VectorAssembler
from sml_tpu.ml.recommendation import ALS


@pytest.fixture()
def blobs_df(spark):
    rng = np.random.default_rng(221)
    centers = np.array([[0.0, 0.0], [5.0, 5.0], [0.0, 6.0]])
    X = np.concatenate([c + rng.normal(0, 0.4, (200, 2)) for c in centers])
    return spark.createDataFrame(pd.DataFrame({"x": X[:, 0], "y": X[:, 1]}))


def test_kmeans_recovers_blobs(blobs_df):
    va = VectorAssembler(inputCols=["x", "y"], outputCol="features")
    km = KMeans(k=3, seed=221, maxIter=20)
    model = km.fit(va.transform(blobs_df))
    centers = np.stack(model.clusterCenters())
    assert centers.shape == (3, 2)
    # each true center has a learned center within 0.3
    true = np.array([[0, 0], [5, 5], [0, 6]], dtype=float)
    for t in true:
        assert np.min(np.linalg.norm(centers - t, axis=1)) < 0.3
    pred = model.transform(va.transform(blobs_df)).toPandas()
    assert pred["prediction"].nunique() == 3
    # maxIter sweep: more iterations can't increase training cost (MLE 02's
    # maxIter experiment)
    costs = [KMeans(k=3, seed=221, maxIter=i).fit(va.transform(blobs_df))
             .summary.trainingCost for i in (1, 5, 20)]
    assert costs[2] <= costs[0] + 1e-3


def test_kmeans_persistence(blobs_df, tmp_path):
    va = VectorAssembler(inputCols=["x", "y"], outputCol="features")
    model = KMeans(k=3, seed=1).fit(va.transform(blobs_df))
    p = str(tmp_path / "km")
    model.write().overwrite().save(p)
    loaded = KMeansModel.load(p)
    assert np.allclose(np.stack(loaded.clusterCenters()),
                       np.stack(model.clusterCenters()))


def _ratings(n_users=60, n_items=40, rank=3, seed=0, frac=0.4):
    rng = np.random.default_rng(seed)
    U = rng.normal(0, 1, (n_users, rank))
    V = rng.normal(0, 1, (n_items, rank))
    full = U @ V.T + 3.0
    mask = rng.random((n_users, n_items)) < frac
    u, i = np.nonzero(mask)
    return pd.DataFrame({"userId": u.astype(np.int64),
                         "movieId": i.astype(np.int64),
                         "rating": full[u, i].astype(np.float64)})


def test_als_fits_low_rank(spark):
    pdf = _ratings()
    df = spark.createDataFrame(pdf)
    train, test = df.randomSplit([0.8, 0.2], seed=42)
    als = ALS(userCol="userId", itemCol="movieId", ratingCol="rating",
              rank=4, maxIter=10, regParam=0.05, seed=42,
              coldStartStrategy="drop")
    model = als.fit(train)
    assert model.rank == 4
    pred = model.transform(test)
    rmse = RegressionEvaluator(labelCol="rating").evaluate(pred)
    # baseline: predict the global mean rating (the MLE 01 baseline pattern)
    tr = train.toPandas()
    te = pred.toPandas()
    base = float(np.sqrt(np.mean((te["rating"] - tr["rating"].mean()) ** 2)))
    assert rmse < base * 0.7


def test_als_cold_start(spark):
    pdf = _ratings(n_users=20, n_items=15)
    df = spark.createDataFrame(pdf)
    als = ALS(userCol="userId", itemCol="movieId", ratingCol="rating",
              rank=3, maxIter=5, seed=1)
    model = als.fit(df)
    unseen = spark.createDataFrame(pd.DataFrame(
        {"userId": [9999], "movieId": [0], "rating": [3.0]}))
    out = model.setColdStartStrategy("nan").transform(unseen).toPandas()
    assert np.isnan(out["prediction"].iloc[0])
    out2 = model.copy({model.getParam("coldStartStrategy"): "drop"}) \
        .transform(unseen)
    assert out2.count() == 0


def test_als_recommendations(spark):
    pdf = _ratings(n_users=25, n_items=30)
    model = ALS(userCol="userId", itemCol="movieId", ratingCol="rating",
                rank=4, maxIter=8, seed=3).fit(spark.createDataFrame(pdf))
    recs = model.recommendForAllUsers(5).toPandas()
    assert len(recs) == 25
    first = recs["recommendations"].iloc[0]
    assert len(first) == 5
    # scores sorted descending
    scores = [r["rating"] for r in first]
    assert scores == sorted(scores, reverse=True)
    assert model.userFactors.count() == 25
    assert model.itemFactors.count() == 30


def test_als_matches_numpy_reference_across_shards(spark):
    """The sorted-segment + compensated-cumsum fit on the 8-shard mesh
    must reproduce a dense float64 numpy ALS with identical inits —
    segments spanning shard boundaries merge via psum, and the
    double-single prefix keeps per-segment sums exact (r4 rewrite)."""
    rng = np.random.default_rng(3)
    n, U, I, r = 40_000, 50, 40, 4
    pdf = pd.DataFrame({
        "user": rng.integers(0, U, n),
        "item": rng.integers(0, I, n),
        "rating": rng.integers(1, 6, n).astype(float),
    })
    df = spark.createDataFrame(pdf)
    REG = 0.1  # shared by the fit and the numpy reference below
    model = ALS(userCol="user", itemCol="item", ratingCol="rating",
                rank=r, maxIter=6, regParam=REG, seed=9).fit(df)
    # factors in raw-id order (np.unique remaps ids; here ids are dense)
    uf = np.asarray(model._uf)
    itf = np.asarray(model._if)

    # independent dense f64 reference with the SAME init draws (the
    # MLlib-style |N(0,1)| unit-norm rows the fit uses)
    init = np.random.default_rng(9)
    uf_ref = np.abs(init.standard_normal((U, r))).astype(np.float64)
    if_ref = np.abs(init.standard_normal((I, r))).astype(np.float64)
    uf_ref /= np.linalg.norm(uf_ref, axis=1, keepdims=True) + 1e-12
    if_ref /= np.linalg.norm(if_ref, axis=1, keepdims=True) + 1e-12
    u = pdf["user"].to_numpy()
    i = pdf["item"].to_numpy()
    rat = pdf["rating"].to_numpy(np.float64)

    def half(ids, other_rows, n_out):
        sol = np.zeros((n_out, r))
        for e in range(n_out):
            m = ids == e
            F = other_rows[m]
            cnt = m.sum()
            A = F.T @ F + REG * max(cnt, 1) * np.eye(r)
            b = F.T @ rat[m]
            if cnt:
                sol[e] = np.linalg.solve(A, b)
        return sol

    for _ in range(6):
        uf_ref = half(u, if_ref[i], U)
        if_ref = half(i, uf_ref[u], I)

    pred = (uf[u] * itf[i]).sum(1)
    pred_ref = (uf_ref[u] * if_ref[i]).sum(1)
    # factors agree to f32-accumulation noise; predictions even tighter
    np.testing.assert_allclose(pred, pred_ref, rtol=2e-3, atol=2e-3)
    rmse = float(np.sqrt(np.mean((pred - rat) ** 2)))
    rmse_ref = float(np.sqrt(np.mean((pred_ref - rat) ** 2)))
    assert abs(rmse - rmse_ref) < 1e-4
