"""Chip placement for task-parallel trials (SURVEY §2.2 P6/P7).

r1 ran CV/hyperopt trials as GIL threads sharing ONE mesh — no trial→chip
placement at all (VERDICT r1 missing #2). Now each trial worker binds a
disjoint submesh of the chip pool.
"""

import time

import numpy as np
import pandas as pd
import pytest

import jax

from sml_tpu.parallel import mesh as meshlib


def test_submeshes_partition_devices():
    meshes = meshlib.submeshes(4)
    devs = [tuple(m.devices.flat) for m in meshes]
    flat = [d for group in devs for d in group]
    assert len(flat) == len(set(flat)) == 8  # disjoint, covering
    assert all(m.axis_names == (meshlib.DATA_AXIS,) for m in meshes)
    # memoized: repeated calls return identical Mesh objects (compile caches
    # key on mesh identity)
    again = meshlib.submeshes(4)
    assert all(a is b for a, b in zip(meshes, again))


def test_submeshes_cycle_when_oversubscribed():
    meshes = meshlib.submeshes(16)
    assert len(meshes) == 16
    assert meshes[0] is meshes[8]


def test_run_placed_trials_binds_disjoint_submeshes():
    seen = {}

    def job(i):
        m = meshlib.get_mesh()
        seen[i] = tuple(m.devices.flat)
        time.sleep(0.05)  # hold the worker so all 4 threads participate
        return i

    out = meshlib.run_placed_trials(list(range(8)), job, parallelism=4)
    assert sorted(out) == list(range(8))
    distinct = set(seen.values())
    assert len(distinct) == 4  # 4 workers → 4 distinct 2-device submeshes
    all_devs = [d for g in distinct for d in g]
    assert len(all_devs) == len(set(all_devs)) == 8


def test_thread_local_mesh_override():
    sub = meshlib.submeshes(4)[0]
    with meshlib.use_mesh_local(sub):
        assert meshlib.get_mesh() is sub
    assert meshlib.get_mesh() is not sub


def test_cv_fits_on_submeshes(spark, airbnb_pdf):
    """CV with parallelism=4 must produce the same numbers as sequential CV
    while actually running trials on submeshes."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder

    df = spark.createDataFrame(airbnb_pdf)
    va = VectorAssembler(inputCols=["bedrooms", "accommodates", "bathrooms"],
                         outputCol="features")
    fdf = va.transform(df)
    lr = LinearRegression(featuresCol="features", labelCol="price")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"),
                                      [0.0, 0.1, 1.0]).build()
    ev = RegressionEvaluator(labelCol="price", metricName="rmse")

    cv_seq = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                            evaluator=ev, numFolds=3, seed=42, parallelism=1)
    cv_par = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                            evaluator=ev, numFolds=3, seed=42, parallelism=4)
    m_seq = cv_seq.fit(fdf)
    m_par = cv_par.fit(fdf)
    np.testing.assert_allclose(m_seq.avgMetrics, m_par.avgMetrics, rtol=1e-5)


@pytest.mark.slow
@pytest.mark.skipif((__import__("os").cpu_count() or 1) < 4,
                    reason="wall-clock trial overlap needs >=4 host cores; "
                           "virtual CPU devices share physical cores, so a "
                           "1-core host serializes everything by construction")
def test_cv_parallel_speedup(spark):
    """parallelism=4 over 8 virtual devices should beat sequential by >2x
    on a device-heavy grid (VERDICT r1 next-round #3). On real chips the
    submeshes are disjoint hardware; here the proxy is disjoint virtual
    CPU devices, which only shows wall-clock wins with enough cores."""
    from sml_tpu.ml.clustering import KMeans
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import RandomForestRegressor
    from sml_tpu.ml.tuning import ParamGridBuilder, TrainValidationSplit

    rng = np.random.default_rng(0)
    n = 20000
    pdf = pd.DataFrame({f"f{i}": rng.normal(size=n) for i in range(8)})
    pdf["label"] = pdf["f0"] * 2 + np.sin(pdf["f1"]) + rng.normal(0, 0.1, n)
    df = spark.createDataFrame(pdf)
    fdf = VectorAssembler(inputCols=[f"f{i}" for i in range(8)],
                          outputCol="features").transform(df)
    rf = RandomForestRegressor(featuresCol="features", labelCol="label",
                               numTrees=12, maxDepth=5, seed=42)
    grid = ParamGridBuilder().addGrid(rf.getParam("numTrees"),
                                      [8, 12, 16, 20]).build()
    ev = RegressionEvaluator(labelCol="label", metricName="rmse")

    def timed(par):
        tvs = TrainValidationSplit(estimator=rf, estimatorParamMaps=grid,
                                   evaluator=ev, seed=42, parallelism=par)
        tvs.fit(fdf)  # warm-up: compiles per submesh config
        t0 = time.perf_counter()
        tvs.fit(fdf)
        return time.perf_counter() - t0

    t_par = timed(4)
    t_seq = timed(1)
    speedup = t_seq / t_par
    # 4 concurrent trials on disjoint 2-device submeshes vs 8-device
    # sequential; demand a real (not incidental) win
    assert speedup > 1.5, f"speedup {speedup:.2f} (seq {t_seq:.2f}s, par {t_par:.2f}s)"


def test_cv_placement_is_logged(spark, airbnb_pdf):
    """Placement is asserted from the log, not wall-clock (VERDICT r2 #7):
    a parallelism=4 CV on the 8-device mesh must record its trials on 4
    distinct disjoint submeshes."""
    from sml_tpu.ml.evaluation import RegressionEvaluator
    from sml_tpu.ml.feature import VectorAssembler
    from sml_tpu.ml.regression import LinearRegression
    from sml_tpu.ml.tuning import CrossValidator, ParamGridBuilder

    df = spark.createDataFrame(airbnb_pdf)
    fdf = VectorAssembler(inputCols=["bedrooms", "accommodates"],
                          outputCol="features").transform(df)
    lr = LinearRegression(labelCol="price")
    grid = ParamGridBuilder().addGrid(lr.getParam("regParam"),
                                      [0.0, 0.01, 0.1, 1.0]).build()
    ev = RegressionEvaluator(labelCol="price")
    mark = len(meshlib.PLACEMENT_LOG)
    CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev,
                   numFolds=3, parallelism=4, seed=42).fit(fdf)
    placed = meshlib.PLACEMENT_LOG[mark:]
    assert len(placed) >= 12  # 4 params x 3 folds
    submeshes_used = {devs for _, devs in placed}
    assert len(submeshes_used) == 4
    flat = [d for g in submeshes_used for d in g]
    assert len(flat) == len(set(flat)) == 8  # disjoint, covering the mesh
